"""Test harness configuration.

Tests run hermetically on the CPU backend with 8 virtual devices so the
multi-chip sharding paths (hash-prefix sharded sketches, OR/max
collectives) are exercised without a TPU pod — SURVEY.md §4.

The axon sitecustomize imports jax at interpreter start, so jax's config
has already captured JAX_PLATFORMS=axon before this file runs — setting
env vars here is too late. Overrides therefore go through the config API
(backends are still uninitialized at conftest-import time, so they take
effect). The persistent compilation cache matters: XLA:CPU compiles of the
larger scatter/gather programs run tens of seconds; caching them on disk
makes every pytest process after the first start warm.
"""

import os
import sys

import pytest

_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")

# Bad-cache preflight (utils/cache.py): the persistent cache on this
# 9p filesystem can go BAD after concurrent/crashed writers (halved
# device counters in the sharded seg/delta-wire tests; numpy segfaults
# in columnar_store.to_columns). Detect the precondition — dir on 9p
# with a stale/other-session bust key — and auto-clear it, replacing
# the manual `rm -rf .jax_cache` folklore. Must run BEFORE jax reads
# the dir.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from attendance_tpu.utils.cache import preflight_cache  # noqa: E402

_verdict = preflight_cache(_CACHE_DIR)
if _verdict == "cleared":
    print("[conftest] .jax_cache matched the documented bad-cache "
          "precondition (9p + stale/other-pid bust key) and was "
          "auto-cleared; first compiles will be cold this run",
          file=sys.stderr)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older JAX: no jax_num_cpu_devices config option. XLA_FLAGS is
    # read when the CPU backend initializes (first device access),
    # which has not happened at conftest-import time, so the env
    # fallback still takes effect — unlike JAX_PLATFORMS, which the
    # sitecustomize jax import captured long ago (module docstring).
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'`; register the marker it filters on.
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from tier-1")


@pytest.fixture
def server():
    """A live BrokerServer for socket-transport tests (one lifecycle
    definition for the transport suite and the CLI smoke tests)."""
    from attendance_tpu.transport.socket_broker import BrokerServer

    srv = BrokerServer().start()
    yield srv
    srv.stop()
