"""Continuous profiling & attribution plane tests (ISSUE 15).

Covers: collapsed-stack correctness on a synthetic two-thread
workload, sampler start/stop hygiene (no leaked thread, no samples
after close), recompile-tracker semantics (once per new shape
fingerprint, zero in steady state, warm contract), the attribution
table golden file, the trend gate's attribution diff on a synthetic
regression pair, the doctor --recompile-ceiling / dispatch-gap /
busy-fraction rows, the fleet headline's top-stage cell, and one
end-to-end fused run with the profiler live (flight-record stage
self-times, gap histogram, artifacts, telemetry --attribution).
"""

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu.obs.profiler import (
    ATTRIBUTION_FILE, FOLDED_FILE, TRACE_FILE, RecompileTracker,
    SamplingProfiler, StageTracker, format_attribution_table)
from attendance_tpu.obs.registry import Registry

REPO = Path(__file__).resolve().parent.parent
DATA = Path(__file__).resolve().parent / "data"


# -- stage tracker -----------------------------------------------------------

def test_stage_tracker_set_restore_nesting():
    st = StageTracker()
    ident = threading.get_ident()
    assert st.get(ident) is None
    prev = st.set("dispatch")
    assert prev is None
    assert st.get(ident) == "dispatch"
    prev2 = st.set("device_wait")
    assert prev2 == "dispatch"
    st.restore(prev2)
    assert st.get(ident) == "dispatch"
    st.restore(prev)
    assert st.get(ident) is None


# -- sampling correctness ----------------------------------------------------

def test_stage_tracker_prunes_dead_thread_marks():
    """CPython recycles thread idents: a dead thread's sticky mark
    must not survive to mislabel whichever thread inherits it."""
    prof = SamplingProfiler(50)
    t = threading.Thread(target=lambda: prof.stages.set("serve"))
    t.start()
    t.join()
    ident = t.ident
    assert prof.stages.get(ident) == "serve"
    prof.sample_once()  # prunes idents absent from _current_frames
    assert prof.stages.get(ident) is None


def _spin_alpha_workload(stop, tracker):
    tracker.set("alpha")
    while not stop.is_set():
        sum(i for i in range(200))


def _spin_beta_workload(stop, tracker):
    tracker.set("beta")
    while not stop.is_set():
        sum(i * i for i in range(200))


def test_collapsed_stacks_two_thread_workload():
    """Two threads spinning in distinctively named functions, each
    marked with its own stage: the collapsed stacks must attribute
    each function to ITS thread's stage — never cross them."""
    prof = SamplingProfiler(97)
    stop = threading.Event()
    threads = [
        threading.Thread(target=_spin_alpha_workload,
                         args=(stop, prof.stages),
                         name="alpha-worker", daemon=True),
        threading.Thread(target=_spin_beta_workload,
                         args=(stop, prof.stages),
                         name="beta-worker", daemon=True),
    ]
    for t in threads:
        t.start()
    try:
        # Drive the sampler deterministically (no background thread):
        # every sample sees both workers mid-spin.
        for _ in range(50):
            prof.sample_once()
            time.sleep(0.002)
    finally:
        stop.set()
        for t in threads:
            t.join()
    collapsed = prof.collapsed()
    alpha_lines = [ln for ln in collapsed.splitlines()
                   if "_spin_alpha_workload" in ln]
    beta_lines = [ln for ln in collapsed.splitlines()
                  if "_spin_beta_workload" in ln]
    assert alpha_lines and beta_lines
    # Stage attribution is per thread: alpha frames carry stage
    # "alpha" on the alpha-worker role, and never stage "beta".
    assert all(ln.startswith("alpha-worker;alpha;")
               for ln in alpha_lines), alpha_lines
    assert all(ln.startswith("beta-worker;beta;")
               for ln in beta_lines), beta_lines
    # Every line is "stack count" with a positive count, and both
    # stages got a meaningful share of the samples.
    for ln in collapsed.splitlines():
        assert int(ln.rsplit(" ", 1)[1]) > 0
    att = prof.attribution()
    assert att["stages"]["alpha"]["samples"] >= 10
    assert att["stages"]["beta"]["samples"] >= 10
    assert att["threads"]["alpha-worker"]["alpha"] \
        == att["stages"]["alpha"]["samples"]


def test_sampler_start_stop_hygiene():
    """No leaked thread after stop, and no samples folded after."""
    prof = SamplingProfiler(211)
    prof.start()
    deadline = time.time() + 5.0
    while prof.samples == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert prof.samples > 0
    prof.stop()
    assert not prof.running
    assert not [t for t in threading.enumerate()
                if t.name == "attendance-profiler"]
    frozen = prof.samples
    time.sleep(3.0 / 211 + 0.05)  # three would-be sampling periods
    assert prof.samples == frozen
    prof.stop()  # idempotent


def test_chrome_trace_merges_consecutive_same_stage_samples():
    prof = SamplingProfiler(97, _clock=time.perf_counter)
    stop = threading.Event()
    t = threading.Thread(target=_spin_alpha_workload,
                         args=(stop, prof.stages),
                         name="alpha-worker", daemon=True)
    t.start()
    try:
        for _ in range(10):
            prof.sample_once()
            time.sleep(0.002)
    finally:
        stop.set()
        t.join()
    doc = prof.chrome_trace()
    slices = [e for e in doc["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "alpha"]
    # 10 consecutive same-stage samples merge into ONE open slice.
    assert len(slices) == 1
    assert doc["otherData"]["samples"] >= 10


def test_stage_fraction_gauges_ride_the_registry():
    reg = Registry()
    prof = SamplingProfiler(50, registry=reg)
    stop = threading.Event()
    t = threading.Thread(target=_spin_alpha_workload,
                         args=(stop, prof.stages),
                         name="alpha-worker", daemon=True)
    t.start()
    try:
        for _ in range(5):
            prof.sample_once()
            time.sleep(0.002)
    finally:
        stop.set()
        t.join()
    from attendance_tpu.obs.exposition import render
    text = render(reg)
    assert "attendance_profile_samples_total" in text
    assert 'attendance_profile_stage_fraction{stage="alpha"}' in text


# -- recompile tracker -------------------------------------------------------

def test_recompile_tracker_fires_once_per_fingerprint():
    reg = Registry()
    rc = RecompileTracker(reg)
    assert rc.observe("step_words", (20, 4096)) is True
    # Steady state: the same fingerprint never fires again.
    for _ in range(100):
        assert rc.observe("step_words", (20, 4096)) is False
    assert rc.observe("step_words", (20, 8192)) is True
    assert rc.observe("step_bytes", (4096,)) is True
    assert rc.total == 3
    assert rc.steady == 0
    rc.mark_warm()
    assert rc.observe("step_words", (20, 4096)) is False  # known
    assert rc.observe("step_words", (21, 4096)) is True  # leak!
    assert rc.total == 4
    assert rc.steady == 1
    snap = rc.snapshot()
    assert snap["total"] == 4 and snap["steady"] == 1
    assert any(fp["steady"] for fp in snap["fingerprints"])
    counters = {(m.name, m.labels): m.value
                for _, _, _, members in reg.collect()
                for m in members}
    assert counters[("attendance_recompiles_total",
                     (("fn", "step_words"),))] == 3
    assert counters[("attendance_recompiles_steady_total",
                     (("fn", "step_words"),))] == 1
    assert counters[("attendance_recompiles_steady_total",
                     (("fn", "step_bytes"),))] == 0


# -- attribution table golden ------------------------------------------------

GOLDEN_DOC = {
    "kind": "attribution", "pid": 7, "hz": 29.0,
    "samples_total": 200, "duration_s": 4.0,
    "stages": {"decode": {"samples": 60, "frac": 0.3},
               "dispatch": {"samples": 120, "frac": 0.6},
               "untagged": {"samples": 20, "frac": 0.1}},
    "threads": {"MainThread": {"decode": 60, "dispatch": 120},
                "snapshot-writer": {"untagged": 20}},
    "recompiles": {"total": 3, "steady": 1, "fingerprints": [
        {"fn": "step_words", "fingerprint": [20, 4096],
         "steady": False},
        {"fn": "step_words", "fingerprint": [20, 8192],
         "steady": True},
    ]},
}


def test_attribution_table_golden():
    rendered = format_attribution_table(GOLDEN_DOC)
    golden = (DATA / "attribution_table.golden").read_text()
    assert rendered == golden.rstrip("\n"), (
        "attribution table drifted from tests/data/"
        "attribution_table.golden:\n" + rendered)


def test_attribution_sniffed_by_format_file(tmp_path):
    from attendance_tpu.obs.exposition import format_file

    p = tmp_path / "attribution.json"
    p.write_text(json.dumps(GOLDEN_DOC))
    out = format_file(str(p))
    assert "dispatch" in out and "60.0%" in out


# -- trend gate attribution diff ---------------------------------------------

HOST = {"cpu_count": 2, "device_kind": "cpu",
        "device_platform": "cpu", "num_devices": 1,
        "platform": "test", "python": "3.10"}


def _obs_artifact(value: float, stages: dict, recompiles=None) -> dict:
    return {
        "metric": "obs_overhead", "value": 0.01, "unit": "fraction",
        "disabled_events_per_sec": value, "host": HOST,
        "attribution": {"hz": 29.0, "samples": 1000,
                        "stages": stages,
                        "recompiles": recompiles
                        or {"total": 2, "steady": 0},
                        "dispatch_gap": {"p50_s": 1e-4,
                                         "p99_s": 2e-3}},
    }


def test_trend_gate_names_injected_stage_delta(tmp_path):
    import sys
    sys.path.insert(0, str(REPO / "tools"))
    import bench_trend

    (tmp_path / "BENCH_OBS_r01.json").write_text(json.dumps(
        _obs_artifact(1_000_000.0,
                      {"dispatch": 0.30, "decode": 0.10,
                       "untagged": 0.60})))
    # -20% regression with the time moving INTO dispatch (and a
    # recompile growth — the classic silent cause).
    (tmp_path / "BENCH_OBS_r02.json").write_text(json.dumps(
        _obs_artifact(800_000.0,
                      {"dispatch": 0.52, "decode": 0.08,
                       "untagged": 0.40},
                      recompiles={"total": 9, "steady": 7})))
    text, ok = bench_trend.run_gate(
        sorted(tmp_path.glob("BENCH*.json")), 0.10)
    assert not ok
    assert "top stage deltas" in text
    assert "dispatch +22.0pp" in text
    assert "recompiles 2->9" in text


def test_trend_gate_attribution_silent_on_pass(tmp_path):
    import sys
    sys.path.insert(0, str(REPO / "tools"))
    import bench_trend

    (tmp_path / "BENCH_OBS_r01.json").write_text(json.dumps(
        _obs_artifact(1_000_000.0, {"dispatch": 0.30})))
    (tmp_path / "BENCH_OBS_r02.json").write_text(json.dumps(
        _obs_artifact(990_000.0, {"dispatch": 0.31})))
    text, ok = bench_trend.run_gate(
        sorted(tmp_path.glob("BENCH*.json")), 0.10)
    assert ok
    assert "top stage deltas" not in text


# -- doctor rows -------------------------------------------------------------

PROM_WITH_ATTRIBUTION = """\
# TYPE attendance_recompiles_total counter
attendance_recompiles_total{fn="step_words"} 3
# TYPE attendance_recompiles_steady_total counter
attendance_recompiles_steady_total{fn="step_words"} %(steady)s
# TYPE attendance_profile_stage_fraction gauge
attendance_profile_stage_fraction{stage="dispatch"} 0.42
attendance_profile_stage_fraction{stage="decode"} 0.11
# TYPE attendance_dispatch_thread_busy_fraction gauge
attendance_dispatch_thread_busy_fraction{component="device_dispatch"} 0.5
attendance_dispatch_thread_busy_fraction{component="temporal"} 0.3
# TYPE attendance_dispatch_gap_seconds histogram
attendance_dispatch_gap_seconds_bucket{le="0.000128"} 10
attendance_dispatch_gap_seconds_bucket{le="+Inf"} 12
attendance_dispatch_gap_seconds_sum 0.01
attendance_dispatch_gap_seconds_count 12
"""


def _doctor(tmp_path, prom_text, **kwargs):
    from attendance_tpu.obs.slo import doctor_report

    p = tmp_path / "m.prom"
    p.write_text(prom_text)
    return doctor_report([str(p)], **kwargs)


def test_doctor_recompile_ceiling_gate(tmp_path):
    text, ok = _doctor(tmp_path, PROM_WITH_ATTRIBUTION % {"steady": 0},
                       recompile_ceiling=0)
    assert ok, text
    assert "steady-state recompiles" in text
    text, ok = _doctor(tmp_path, PROM_WITH_ATTRIBUTION % {"steady": 2},
                       recompile_ceiling=0)
    assert not ok
    assert "steady-state recompiles" in text


def test_doctor_recompile_ceiling_fails_loudly_when_absent(tmp_path):
    # A ceiling over a run whose telemetry never exported the tracker
    # must FAIL (vacuous-pass refusal, the merge-lag precedent).
    text, ok = _doctor(
        tmp_path, "# TYPE attendance_events_total counter\n"
        "attendance_events_total 5\n"
        "# TYPE attendance_slo_firing gauge\n",
        recompile_ceiling=0)
    assert not ok
    assert "steady-state recompiles" in text


def test_doctor_attribution_info_rows(tmp_path):
    text, ok = _doctor(tmp_path, PROM_WITH_ATTRIBUTION % {"steady": 3})
    assert ok, text  # no ceiling: info rows only
    assert "profiled top stages" in text
    assert "dispatch 42%" in text
    assert "dispatch thread occupancy" in text
    assert "temporal 30%" in text
    assert "dispatch gap p50/p99" in text
    assert "device recompiles (total, incl. warmup)" in text
    assert "steady-state recompiles (shape leak?)" in text


def test_doctor_fleet_recompile_gate(tmp_path):
    from attendance_tpu.obs.slo import doctor_fleet_report

    fleet = tmp_path / "fleet"
    fleet.mkdir()
    (fleet / "worker@w0.prom").write_text(
        PROM_WITH_ATTRIBUTION % {"steady": 0})
    (fleet / "serve@s0.prom").write_text(
        "# TYPE attendance_events_total counter\n"
        "attendance_events_total 5\n")
    text, ok = doctor_fleet_report(str(fleet), recompile_ceiling=0)
    assert ok, text
    assert "fleet: steady-state recompiles" in text
    (fleet / "worker@w0.prom").write_text(
        PROM_WITH_ATTRIBUTION % {"steady": 4})
    text, ok = doctor_fleet_report(str(fleet), recompile_ceiling=0)
    assert not ok


# -- fleet headline ----------------------------------------------------------

def test_fleet_headline_top_stage():
    from attendance_tpu.obs.fleet import _headline

    out = _headline(PROM_WITH_ATTRIBUTION % {"steady": 0})
    assert out["top_stage"] == "dispatch 42%"


# -- end to end: fused run under the profiler --------------------------------

@pytest.fixture
def obs_off():
    from attendance_tpu import obs

    obs.disable()
    yield
    obs.disable()


def test_fused_run_profiled_end_to_end(tmp_path, obs_off, capsys):
    from attendance_tpu import obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames

    prof_dir = tmp_path / "profile"
    # json_chunk_decode off: the chunk consumer coalesces backlog
    # frames into timing-dependent padded shapes — legitimate new
    # programs, not the leak class the steady-recompile assert gates.
    cfg = Config(profile_hz=97, profile_out=str(prof_dir),
                 flight_recorder=32, json_chunk_decode=False)
    t = obs.enable(cfg)
    pipe = FusedPipeline(cfg)
    try:
        roster, frames = generate_frames(
            24_576, 4096, roster_size=8_000, num_lectures=4, seed=3)
        pipe.preload(roster)
        producer = pipe.client.create_producer(cfg.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=24_576, idle_timeout_s=0.5)
        # Warmup compiled something; nothing after run 1 may.
        assert t.recompiles.total > 0
        assert t.recompiles.warm
        steady_before = t.recompiles.steady
        # SAME seed: a different seed's roster can change the max-key
        # bit width — a genuinely new program, not the leak class this
        # asserts on (idempotent sketches make the replay harmless).
        _, frames2 = generate_frames(
            24_576, 4096, roster_size=8_000, num_lectures=4, seed=3)
        for f in frames2:
            producer.send(f)
        pipe.run(max_events=49_152, idle_timeout_s=0.5)
        assert t.recompiles.steady == steady_before == 0
        # Flight records carry per-stage self-times (SIGUSR1
        # attributability without the trace file).
        rec = t.flight.snapshot()[-1]
        stages = rec["stages"]
        for key in ("dequeue_wait", "decode", "dispatch",
                    "device_wait"):
            assert key in stages
        assert stages["decode"] >= 0.0
        # Dispatch-gap histogram observed between frames.
        gap = t.registry.histogram("attendance_dispatch_gap_seconds")
        assert gap.count > 0
        # Busy-fraction gauges render (decode/device_dispatch/
        # device_wait; no temporal component without the plane).
        text = t.render()
        assert ('attendance_dispatch_thread_busy_fraction'
                '{component="device_dispatch"}') in text
        assert 'component="temporal"' not in text
        assert "attendance_device_transfer_bytes_total" in text
        assert "attendance_recompiles_steady_total" in text
    finally:
        pipe.cleanup()
        obs.disable()
    # Artifacts written at stop; the CLI renders the table.
    for name in (FOLDED_FILE, TRACE_FILE, ATTRIBUTION_FILE):
        assert (prof_dir / name).exists(), name
    doc = json.loads((prof_dir / ATTRIBUTION_FILE).read_text())
    assert doc["kind"] == "attribution"
    assert doc["samples_total"] > 0
    assert doc["recompiles"]["total"] > 0
    from attendance_tpu import cli

    cli.main(["telemetry", str(prof_dir), "--attribution"])
    out = capsys.readouterr().out
    assert "attribution:" in out and "stage" in out
    assert "recompiles:" in out


def test_telemetry_attribution_missing_artifact_exits_2(tmp_path):
    from attendance_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["telemetry", str(tmp_path / "nope"),
                  "--attribution"])
    assert exc.value.code == 2


def test_profile_out_without_hz_is_a_config_error(tmp_path):
    from attendance_tpu.config import Config

    with pytest.raises(ValueError, match="profile-hz"):
        Config(profile_out=str(tmp_path)).validate()
    Config(profile_out=str(tmp_path), profile_hz=29).validate()


def test_run_resets_dispatch_gap_cursor(obs_off):
    """The inter-run idle must never land in the gap histogram: a
    later run's first frame would otherwise record minutes of wall
    clock as one 'dispatch gap' and own the p99 forever."""
    from attendance_tpu import obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    cfg = Config(flight_recorder=8)
    obs.enable(cfg)
    pipe = FusedPipeline(cfg)
    try:
        pipe._last_dispatch_t = 123.0  # stale cursor from a past run
        pipe.run(max_events=0, idle_timeout_s=0.05)  # empty broker
        assert pipe._last_dispatch_t == 0.0
    finally:
        pipe.cleanup()
        obs.disable()


def test_doctor_top_stages_rank_tagged_above_untagged(tmp_path):
    """Same ordering as the fleet dashboard's top_stage cell: a
    sample-heavy untagged bucket must not displace real stages."""
    prom = (PROM_WITH_ATTRIBUTION % {"steady": 0}
            + 'attendance_profile_stage_fraction{stage="untagged"}'
            + " 0.9\n")
    text, ok = _doctor(tmp_path, prom)
    assert ok
    row = next(l for l in text.splitlines()
               if "profiled top stages" in l)
    assert "dispatch 42%" in row
    assert "untagged" not in row
