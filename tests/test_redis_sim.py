"""Redis-exact hermetic oracle tests (VERDICT r02 #1).

Round 2's parity harness paired the TPU store against the memory store —
a bit-identical mirror of the same hash design, which cannot catch a
systematic bias shared by both. These tests pair the TPU store against
``RedisSimSketchStore``: a pure-numpy simulation of Redis's actual
algorithms (RedisBloom sizing + MurmurHash64A double hashing over
decimal-string members; dense-HLL hllPatLen + the Ertl estimator), so
the north-star budgets — no false negatives, FPR <= 1%, HLL error <= 2%
(BASELINE.md; reference attendance_processor.py:83-88,109-113,129,152)
— are asserted against Redis's real math with no shared hashing.
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.parity import run_parity
from attendance_tpu.sketch.base import ResponseError
from attendance_tpu.sketch.redis_sim import (
    HLL_P, HLL_Q, RedisSimSketchStore, hash_members_u64, murmur64a_fixed,
    murmur64a_scalar, sim_bloom_params, sim_hll_bucket_rank)
from attendance_tpu.sketch.tpu_store import TpuSketchStore


def _sim():
    return RedisSimSketchStore(Config(sketch_backend="redis-sim"))


# ---------------------------------------------------------------------------
# MurmurHash64A
# ---------------------------------------------------------------------------

class TestMurmur64A:
    def test_vectorized_matches_scalar_all_tail_lengths(self):
        """Block loop + every tail length (0..7 mod 8) against the
        plain-Python transcription of Appleby's algorithm."""
        rng = np.random.default_rng(7)
        for length in [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 15, 16, 17, 24]:
            data = rng.integers(0, 256, size=(32, length), dtype=np.uint8)
            vec = murmur64a_fixed(data, 0xADC83B19)
            for i in range(len(data)):
                assert int(vec[i]) == murmur64a_scalar(
                    bytes(data[i]), 0xADC83B19), (length, i)

    def test_per_element_seeds(self):
        """The Bloom b-lane seeds each element's second hash with its
        first — the vectorized path must honor per-element seeds."""
        rng = np.random.default_rng(8)
        data = rng.integers(0, 256, size=(40, 9), dtype=np.uint8)
        seeds = rng.integers(0, 2 ** 63, size=40, dtype=np.uint64)
        vec = murmur64a_fixed(data, seeds)
        for i in range(len(data)):
            assert int(vec[i]) == murmur64a_scalar(
                bytes(data[i]), int(seeds[i]))

    def test_members_hash_as_decimal_strings(self):
        """Key 12345 hashes the bytes b'12345' — what redis-py sends
        for the reference's integer student IDs
        (reference data_generator.py:53-54)."""
        keys = np.array([0, 5, 9, 10, 99, 12345, 99999, 2 ** 32 - 1],
                        dtype=np.uint32)
        h = hash_members_u64(keys, 0xADC83B19)
        for i, k in enumerate(keys):
            assert int(h[i]) == murmur64a_scalar(
                str(int(k)).encode(), 0xADC83B19), k


# ---------------------------------------------------------------------------
# RedisBloom sizing + semantics
# ---------------------------------------------------------------------------

class TestSimBloom:
    def test_reference_reserve_sizing(self):
        """The reference's BF.RESERVE bf 0.01 100000
        (attendance_processor.py:83-88): bpe=9.585 -> 958506 raw bits,
        rounded up to 2^20; k = ceil(ln2 * bpe) = 7; capacity scaled up
        to bits/bpe."""
        p = sim_bloom_params(100_000, 0.01)
        assert p.m_bits == 1 << 20
        assert p.k == 7
        assert p.capacity == int((1 << 20) / (-np.log(0.01) / 0.480453013918201))
        assert p.capacity > 100_000  # power-of-two rounding adds headroom

    def test_power_of_two_rounding_always_rounds_up(self):
        # Even an exact power of two goes up one (bloom.c: n2 = logb+1).
        bpe = -np.log(0.01) / 0.480453013918201
        entries = int((1 << 16) / bpe) + 1
        p = sim_bloom_params(entries, 0.01)
        assert p.m_bits == 1 << 17

    def test_bad_args_raise(self):
        with pytest.raises(ResponseError):
            sim_bloom_params(100, 0.0)
        with pytest.raises(ResponseError):
            sim_bloom_params(100, 1.0)
        with pytest.raises(ResponseError):
            sim_bloom_params(0, 0.01)

    def test_reserve_twice_raises_item_exists(self):
        store = _sim()
        store.execute_command("BF.RESERVE", "bf", 0.01, 1000)
        with pytest.raises(ResponseError):
            store.execute_command("BF.RESERVE", "bf", 0.01, 1000)

    def test_no_false_negatives_and_fpr_budget(self):
        store = _sim()
        store.bf_reserve("bf", 0.01, 10_000)
        rng = np.random.default_rng(11)
        roster = rng.choice(np.arange(10_000, 500_000, dtype=np.uint32),
                            10_000, replace=False)
        store.bf_add_many("bf", roster)
        assert store.bf_exists_many("bf", roster).all()
        invalid = np.arange(600_000, 640_000, dtype=np.uint32)
        fpr = float(store.bf_exists_many("bf", invalid).mean())
        assert fpr <= 0.01 + 3 * np.sqrt(0.01 * 0.99 / len(invalid))

    def test_auto_create_and_scaling_chain(self):
        """BF.ADD on a missing key creates a default filter (capacity
        100, error 0.01) that auto-scales by chaining — RedisBloom
        SBChain behavior with expansion 2, tightening 0.5."""
        store = _sim()
        keys = np.arange(5_000, dtype=np.uint32) + 1
        added = store.bf_add_many("auto", keys)
        assert added.all()
        chain = store._blooms["auto"]
        assert len(chain.filters) > 1
        # Re-adding reports nothing new; membership still complete.
        assert not store.bf_add_many("auto", keys).any()
        assert store.bf_exists_many("auto", keys).all()
        info = store.execute_command("BF.INFO", "auto")
        assert info["Number of filters"] == len(chain.filters)
        assert info["Number of items inserted"] == 5_000

    def test_madd_duplicate_members_report_added_once(self):
        """A real server processes BF.MADD members sequentially: the
        second copy of a duplicate sees the first's bits —
        BF.MADD k 7 7 answers [1, 0] — and capacity accounting counts
        distinct members once."""
        store = _sim()
        store.bf_reserve("bf", 0.01, 1000)
        out = store.execute_command("BF.MADD", "bf", 7, 7, 8, 7)
        assert out == [1, 0, 1, 0]
        assert store._blooms["bf"].item_count == 2

    def test_missing_key_exists_returns_zeros(self):
        store = _sim()
        assert not store.bf_exists_many(
            "nope", np.arange(10, dtype=np.uint32)).any()

    def test_madd_crossing_grow_boundary_inserts_in_call_order(self):
        """A real server processes BF.MADD members sequentially, so
        when one call crosses a sub-filter grow boundary, which keys
        land in the old vs the new sub-filter follows CALL order — one
        bulk MADD must leave the chain bit-identical to the same keys
        added one at a time (ADVICE r03: np.unique's sorted order
        diverged here)."""
        rng = np.random.default_rng(5)
        keys = rng.permutation(
            np.arange(1, 301, dtype=np.uint32))  # shuffled, not sorted

        # eps=1e-4 keeps intra-call false positives improbable: the one
        # sequential-processing effect add_many deliberately does NOT
        # mirror (documented in its docstring) is a later member
        # colliding with bits set earlier in the same call, and at the
        # default 0.01 that confounds the order property under test.
        bulk = _sim()
        bulk.bf_reserve("bf", 0.0001, 200)  # 300 keys -> grows mid-call
        bulk.bf_add_many("bf", keys)

        seq = _sim()
        seq.bf_reserve("bf", 0.0001, 200)
        for k in keys:
            seq.bf_add_many("bf", np.array([k], np.uint32))

        cb, cs = bulk._blooms["bf"], seq._blooms["bf"]
        assert len(cb.filters) == len(cs.filters) > 1
        assert cb.counts == cs.counts
        for fb, fs in zip(cb.filters, cs.filters):
            np.testing.assert_array_equal(fb, fs)


# ---------------------------------------------------------------------------
# Redis dense HLL semantics
# ---------------------------------------------------------------------------

class TestSimHLL:
    def test_bucket_rank_law(self):
        """index = low-14 bits of mm64a(member, 0xadc83b19); rank =
        1 + trailing zeros of the remaining bits with the q-bit guard —
        Redis hllPatLen, checked member by member."""
        keys = np.arange(1, 300, dtype=np.uint32) * 7919
        idx, rank = sim_hll_bucket_rank(keys)
        for i, k in enumerate(keys):
            h = murmur64a_scalar(str(int(k)).encode(), 0xADC83B19)
            assert int(idx[i]) == h & ((1 << HLL_P) - 1)
            rest = (h >> HLL_P) | (1 << HLL_Q)
            expect = 1
            while rest & 1 == 0:
                expect += 1
                rest >>= 1
            assert int(rank[i]) == expect, k
        assert rank.max() <= HLL_Q + 1

    def test_pfadd_change_semantics(self):
        store = _sim()
        assert store.pfadd("h", 42) == 1          # register rose
        assert store.pfadd("h", 42) == 0          # idempotent re-add
        assert store.pfadd("h2") == 1             # bare PFADD creates
        assert store.pfadd("h2") == 0             # ...once
        assert store.pfcount("missing") == 0

    def test_pfcount_union_is_register_max(self):
        store = _sim()
        a = np.arange(0, 30_000, dtype=np.uint32)
        b = np.arange(20_000, 50_000, dtype=np.uint32)
        store.pfadd_many("ha", a)
        store.pfadd_many("hb", b)
        union = store.pfcount("ha", "hb")
        assert abs(union - 50_000) / 50_000 < 0.02


# ---------------------------------------------------------------------------
# The deliverable: TPU vs simulated-Redis parity, cardinalities 10..10M
# ---------------------------------------------------------------------------

class TestTpuVsRedisSimParity:
    def test_full_parity_harness(self):
        """The reference event stream driven through both backends via
        the exact redis-py call shapes; budgets asserted against the
        simulated-Redis answers (VERDICT r02 #1 'done' criterion)."""
        report = run_parity(
            TpuSketchStore(Config(sketch_backend="tpu")),
            _sim(),
            num_events=50_000, roster_size=10_000, num_lectures=4, seed=5)
        assert report.ok, report.summary()
        assert report.false_negatives_a == 0
        assert report.false_negatives_b == 0
        assert report.fpr_a <= report.fpr_limit
        assert report.fpr_b <= report.fpr_limit
        assert report.hll_err_a <= 0.02
        assert report.hll_err_b <= 0.02
        from attendance_tpu.parity import HLL_CROSS_LIMIT
        assert report.hll_cross_err <= HLL_CROSS_LIMIT

    @pytest.mark.parametrize("cardinality", [10, 10_000, 1_000_000,
                                             10_000_000])
    def test_hll_cardinality_sweep(self, cardinality):
        """PFCOUNT within 2% of exact on BOTH backends, and of each
        other, from 10 to 10M distinct members — the full range the
        north star spans (10M-student roster, BASELINE.md)."""
        tpu = TpuSketchStore(Config(sketch_backend="tpu"))
        sim = _sim()
        members = np.arange(cardinality, dtype=np.uint32) + 10_000
        chunk = 1 << 17  # one compiled shape for the device scatter
        for i in range(0, cardinality, chunk):
            tpu.pfadd_many("h", members[i:i + chunk])
        sim.pfadd_many("h", members)
        est_tpu = tpu.pfcount("h")
        est_sim = sim.pfcount("h")
        tol = 0.02
        assert abs(est_tpu - cardinality) / cardinality <= tol, est_tpu
        assert abs(est_sim - cardinality) / cardinality <= tol, est_sim
        assert abs(est_tpu - est_sim) / cardinality <= tol

    def test_bloom_agreement_at_reference_scale(self):
        """The reference's own configuration (capacity 100k, eps 0.01,
        README.md:104) with a full roster: both backends answer every
        roster member yes; disagreements limited to the FPR budget."""
        tpu = TpuSketchStore(Config(sketch_backend="tpu"))
        sim = _sim()
        rng = np.random.default_rng(13)
        roster = rng.choice(np.arange(10_000, 10_000_000, dtype=np.uint32),
                            100_000, replace=False)
        probe = np.arange(20_000_000, 20_050_000, dtype=np.uint32)
        for store in (tpu, sim):
            store.bf_reserve("bf", 0.01, 100_000)
            store.bf_add_many("bf", roster)
            assert store.bf_exists_many("bf", roster).all()
        fp_tpu = float(tpu.bf_exists_many("bf", probe).mean())
        fp_sim = float(sim.bf_exists_many("bf", probe).mean())
        allow = 0.01 + 3 * np.sqrt(0.01 * 0.99 / len(probe))
        assert fp_tpu <= allow, fp_tpu
        assert fp_sim <= allow, fp_sim


@pytest.mark.parametrize("make_store", [
    _sim,
    lambda: __import__("attendance_tpu.sketch.tpu_store",
                       fromlist=["TpuSketchStore"]).TpuSketchStore(
        Config(sketch_backend="tpu")),
], ids=["redis-sim", "tpu"])
def test_scaling_chain_keeps_compound_fpr_budget(make_store):
    """Auto-scaling exists to BOUND error, not just to fit keys: with
    per-level error tightening (e0/2^i), the whole chain's FPR stays
    <= ~2*e0 no matter how far an implicit filter grows past its
    default capacity (RedisBloom's own guarantee). 50x overflow of the
    default-100 filter, probed with a disjoint population."""
    store = make_store()
    keys = np.arange(10_000, 15_000, dtype=np.uint32)  # 50x default cap
    store.bf_add_many("auto", keys)
    assert store.bf_exists_many("auto", keys).all()  # never lose members
    probe = np.arange(1_000_000, 1_040_000, dtype=np.uint32)
    fpr = float(store.bf_exists_many("auto", probe).mean())
    e0 = 0.01  # DEFAULT_ERROR_RATE
    assert fpr <= 2 * e0 + 3 * np.sqrt(2 * e0 * (1 - 2 * e0) / len(probe)), fpr
