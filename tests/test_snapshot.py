"""Snapshot/restore: device->disk->device round-trip (SURVEY.md §5).

A restored store must answer every query exactly like the original: same
Bloom memberships (bit-identical arrays), same PFCOUNTs, same scalable-
chain bookkeeping.
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.sketch.memory_store import MemorySketchStore
from attendance_tpu.sketch.tpu_store import TpuSketchStore
from attendance_tpu.utils.snapshot import (
    restore_sketch_store, snapshot_sketch_store)


def populated(store_cls):
    store = store_cls(Config(sketch_backend="memory"))
    store.bf_reserve("bf:students", 0.01, 10_000)
    store.bf_add_many("bf:students", np.arange(1000, 4000, dtype=np.int64))
    # second filter with auto-created defaults, forcing chain growth
    store.bf_add_many("bf:other", np.arange(500, dtype=np.int64))
    store.pfadd_many("hll:unique:LECTURE_1",
                     np.arange(2000, dtype=np.int64))
    store.pfadd_many("hll:unique:LECTURE_2",
                     np.arange(50, dtype=np.int64))
    return store


@pytest.mark.parametrize("store_cls", [MemorySketchStore, TpuSketchStore])
def test_snapshot_roundtrip(store_cls, tmp_path):
    store = populated(store_cls)
    path = tmp_path / "sketch.npz"
    manifest = snapshot_sketch_store(store, path)
    assert "bf:students" in manifest["blooms"]

    restored = store_cls(Config(sketch_backend="memory"))
    restore_sketch_store(restored, path)

    probe = np.arange(0, 8000, dtype=np.int64)
    np.testing.assert_array_equal(
        np.asarray(store.bf_exists_many("bf:students", probe)),
        np.asarray(restored.bf_exists_many("bf:students", probe)))
    np.testing.assert_array_equal(
        np.asarray(store.bf_exists_many("bf:other", probe)),
        np.asarray(restored.bf_exists_many("bf:other", probe)))
    for key in ("hll:unique:LECTURE_1", "hll:unique:LECTURE_2"):
        assert store.pfcount(key) == restored.pfcount(key)
    assert (restored.pfcount("hll:unique:LECTURE_1",
                             "hll:unique:LECTURE_2")
            == store.pfcount("hll:unique:LECTURE_1",
                             "hll:unique:LECTURE_2"))
    # chain bookkeeping survives: adding past capacity still auto-scales
    b = restored._blooms["bf:other"]
    assert b.item_count == 500
    assert len(b.filters) >= 2  # 500 inserts > default capacity 100


def test_restore_then_continue_writing(tmp_path):
    store = populated(MemorySketchStore)
    path = tmp_path / "s.npz"
    snapshot_sketch_store(store, path)
    restored = MemorySketchStore(Config(sketch_backend="memory"))
    restore_sketch_store(restored, path)
    # replaying already-seen members is idempotent; new members register
    before = restored.pfcount("hll:unique:LECTURE_2")
    restored.pfadd_many("hll:unique:LECTURE_2",
                        np.arange(50, dtype=np.int64))  # replay
    assert restored.pfcount("hll:unique:LECTURE_2") == before
    restored.bf_add_many("bf:students", np.array([9999]))
    assert restored.bf_exists_many("bf:students", np.array([9999]))[0]
