"""Accuracy auditing + SLO engine + doctor verdict (obs/audit, obs/slo).

Covers: hash-partition sampling, measured-FPR/false-negative/HLL-error
cross-checks against an exact offline recount (store path and fused
path), the burn-rate window math (fires on sustained breach, rejects a
single-window spike, clears with hysteresis), the alert log + flight
cross-reference, Histogram.quantile and its exposition twin, health
gauges surviving snapshot restore (restore-then-scrape), and the
``doctor`` verdict table golden file with its exit-code contract.
"""

import json
import math
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.obs.audit import ShadowAuditor
from attendance_tpu.obs.registry import Registry, quantile_from_buckets
from attendance_tpu.obs.slo import (
    SloEngine, doctor_report, parse_slo, resolve_slos)
from attendance_tpu.sketch import make_sketch_store

GOLDEN = Path(__file__).parent / "data" / "doctor_verdict.golden"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    obs.disable()
    yield
    obs.disable()


# -- sampling ----------------------------------------------------------------

def test_sample_mask_is_a_hash_partition():
    """Sequential keys (the reference's roster shape) sample at ~the
    requested fraction, and the mask is a pure function of the key —
    the same key is sampled on add and on query."""
    reg = Registry()
    aud = ShadowAuditor(reg, 0.1)
    keys = np.arange(100_000, dtype=np.uint32)
    mask = aud.sample_mask(keys)
    assert 0.08 < mask.mean() < 0.12
    np.testing.assert_array_equal(mask, aud.sample_mask(keys))


# -- store-path auditing -----------------------------------------------------

def _audited_store(sample: float):
    cfg = Config(sketch_backend="memory", audit_sample=sample,
                 bloom_filter_capacity=2_000)
    t = obs.enable(cfg)
    return t, cfg, make_sketch_store(cfg)


def test_measured_fpr_agrees_with_exact_offline_recount():
    """The acceptance scenario at store level: the measured-FPR gauge
    must equal an independent recount over the sampled keys — sampled
    negative queries classified by true roster membership, false
    positives by the store's own answers."""
    t, cfg, store = _audited_store(0.25)
    roster = np.arange(1_000, dtype=np.int64)
    store.bf_add_many(cfg.bloom_filter_key, roster)
    queries = np.arange(500, 3_000, dtype=np.int64)
    answers = np.asarray(
        store.bf_exists_many(cfg.bloom_filter_key, queries))

    aud = t.auditor
    mask = aud.sample_mask(queries.astype(np.uint32))
    in_roster = queries < 1_000  # exact membership, by construction
    negatives = int((mask & ~in_roster).sum())
    fps = int((mask & ~in_roster & answers).sum())
    assert negatives > 0
    assert aud._negatives.value == negatives
    assert aud._fp.value == fps
    assert aud.measured_fpr() == pytest.approx(fps / negatives)
    # Structural invariant: an added key can never answer absent.
    assert aud._fn.value == 0
    text = t.render()
    assert "attendance_bloom_measured_fpr" in text
    assert "attendance_bloom_false_negatives_total 0" in text


def test_hll_measured_rel_error_agrees_with_exact_recount():
    """At sample=1.0 the shadow is the full ground truth, so the gauge
    must equal |PFCOUNT - exact|/exact to float precision."""
    t, cfg, store = _audited_store(1.0)
    key = f"{cfg.hll_key_prefix}LECTURE_1"
    members = np.arange(5_000, dtype=np.int64)
    store.pfadd_many(key, members)
    store.pfadd_many(key, members[:1_000])  # duplicates change nothing
    est = store.pfcount(key)
    expected = abs(est - 5_000) / 5_000
    g = t.registry.gauge("attendance_hll_measured_rel_error", key=key)
    assert g.value == pytest.approx(expected)
    assert expected < 0.02  # the ROADMAP ceiling holds on this run


def test_false_negative_is_detected_and_screamed():
    """A lying sketch (answers absent for added keys) must increment
    the must-stay-zero counter — the auditor exists to catch exactly
    this class of kernel bug in production."""
    reg = Registry()
    aud = ShadowAuditor(reg, 1.0)
    keys = np.arange(100, dtype=np.uint32)
    aud.record_bf_add("bf", keys)
    aud.check_bf_exists("bf", keys, np.zeros(100, dtype=bool))
    assert aud._fn.value == 100


def test_unaudited_runs_pay_nothing():
    """audit_sample=0 leaves no auditor anywhere: stores hold None and
    pay one branch per command."""
    cfg = Config(sketch_backend="memory")
    store = make_sketch_store(cfg)
    assert store._auditor is None
    assert obs.get() is None


def test_redis_sim_answers_are_audited_too():
    """The simulated-Redis backend reimplements the command surface
    wholesale; its overrides moved to the _u32 chokepoints so the
    audit still sees every answer."""
    cfg = Config(sketch_backend="redis-sim", audit_sample=1.0)
    t = obs.enable(cfg)
    store = make_sketch_store(cfg)
    store.bf_add_many("bf:students", np.arange(500, dtype=np.int64))
    store.bf_exists_many("bf:students",
                         np.arange(1_000, dtype=np.int64))
    assert t.auditor._negatives.value == 500
    assert t.auditor._fn.value == 0


# -- fused-path auditing -----------------------------------------------------

def _fused_run(config, num_events=4_096, frame=1_024, roster_size=4_000):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(num_events, frame,
                                     roster_size=roster_size,
                                     num_lectures=4)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.3)
    return pipe, roster


def test_fused_audit_gauges_agree_with_exact_recount():
    config = Config(bloom_filter_capacity=5_000, audit_sample=1.0)
    t = obs.enable(config)
    pipe, roster = _fused_run(config)

    # Exact ground truth at sample=1.0: recount the stored traffic
    # against the true roster, independently of the auditor.
    cols = pipe.store.to_columns(deduplicate=False)
    sids = np.asarray(cols["student_id"], dtype=np.uint32)
    days = np.asarray(cols["lecture_day"])
    roster_set = set(int(k) for k in roster)
    valid = np.fromiter((int(s) in roster_set for s in sids),
                        dtype=bool, count=len(sids))
    exact_per_day = {}
    for d, s in zip(days[valid], sids[valid]):
        exact_per_day.setdefault(int(d), set()).add(int(s))
    truth_total = sum(len(v) for v in exact_per_day.values())
    est_total = sum(pipe.count_all().values())
    expected_rel = abs(est_total - truth_total) / truth_total

    g_err = t.registry.gauge("attendance_hll_measured_rel_error",
                             key="fused")
    assert g_err.value == pytest.approx(expected_rel, abs=1e-9)
    assert expected_rel < 0.02

    # Measured FPR: the scrape-time device re-query over the sampled
    # negative traffic, vs an offline re-query of the same probe set.
    g_fpr = t.registry.gauge("attendance_bloom_measured_fpr",
                             surface="fused")
    measured = g_fpr.value
    from attendance_tpu.models.bloom import bloom_contains_words
    negatives = np.fromiter((int(s) for s in set(sids.tolist())
                             - roster_set), dtype=np.uint32)
    answers = np.asarray(bloom_contains_words(
        pipe.state.bloom_bits, negatives, pipe.params))
    assert measured == pytest.approx(answers.mean())
    assert t.auditor._fn.value == 0  # no roster key answered absent


# -- SLO window math ---------------------------------------------------------

def _engine(tmp_path, **kw):
    t = obs.enable(Config(flight_recorder=8))
    eng = SloEngine(t, (), fast_s=4.0, slow_s=20.0,
                    path=str(tmp_path / "alerts.jsonl"), **kw)
    fpr = t.registry.gauge("attendance_bloom_measured_fpr")
    return t, eng, fpr, eng._state["bloom_measured_fpr"]


def test_sustained_breach_fires(tmp_path):
    t, eng, fpr, st = _engine(tmp_path)
    fpr.set(0.005)
    for i in range(25):
        eng.tick(now=float(i))
    assert not st.firing
    fpr.set(0.05)
    for i in range(25, 50):
        eng.tick(now=float(i))
    assert st.firing
    events = [json.loads(l) for l in
              (tmp_path / "alerts.jsonl").read_text().splitlines()]
    assert events[-1]["slo"] == "bloom_measured_fpr"
    assert events[-1]["state"] == "firing"
    assert events[-1]["burn_fast"] >= eng.fire_burn
    assert events[-1]["burn_slow"] >= eng.fire_burn
    # The transition is flagged in the flight ring for forensics.
    alerts = [r for r in t.flight.snapshot() if "alert" in r]
    assert alerts and alerts[-1]["alert"] == "bloom_measured_fpr"
    # ...and the burn gauges are on the scrape surface.
    text = t.render()
    assert 'attendance_slo_firing{slo="bloom_measured_fpr"} 1' in text
    assert "attendance_slo_burn_rate" in text


def test_single_window_spike_does_not_fire(tmp_path):
    """A spike shorter than fire_burn * budget of the slow window must
    not page — the classic multi-window rationale."""
    t, eng, fpr, st = _engine(tmp_path)
    fpr.set(0.005)
    for i in range(21):
        eng.tick(now=float(i))
    fpr.set(0.05)  # 2-tick spike: 10% of the slow window
    for i in range(21, 23):
        eng.tick(now=float(i))
    fpr.set(0.005)
    for i in range(23, 44):
        eng.tick(now=float(i))
    assert not st.firing
    log = tmp_path / "alerts.jsonl"
    assert not log.exists() or log.read_text() == ""


def test_alert_clears_with_hysteresis(tmp_path):
    t, eng, fpr, st = _engine(tmp_path)
    fpr.set(0.05)
    for i in range(25):
        eng.tick(now=float(i))
    assert st.firing
    # Oscillation around the ceiling: breaches keep landing in the
    # fast window — burn stays above the clear threshold, no flapping.
    for i in range(25, 33):
        fpr.set(0.05 if i % 2 else 0.005)
        eng.tick(now=float(i))
    assert st.firing
    # Sustained recovery: the fast window drains below half the firing
    # burn and the alert resolves exactly once.
    fpr.set(0.001)
    for i in range(33, 45):
        eng.tick(now=float(i))
    assert not st.firing
    states = [json.loads(l)["state"] for l in
              (tmp_path / "alerts.jsonl").read_text().splitlines()]
    assert states == ["firing", "resolved"]


def test_first_tick_breach_does_not_fire(tmp_path):
    """The burn denominator is the window's EXPECTED sample count: one
    transiently-bad tick in a near-empty window must not page (a
    1-sample window would otherwise read as a 100%-breach window)."""
    t, eng, fpr, st = _engine(tmp_path)
    fpr.set(0.05)
    eng.tick(now=0.0)
    eng.tick(now=1.0)
    assert not st.firing
    log = tmp_path / "alerts.jsonl"
    assert not log.exists() or log.read_text() == ""


def test_roster_shadow_overflow_disables_fused_audit(monkeypatch):
    """A roster larger than the shadow cap must STOP the fused
    measurement (empty probe sets, NaN gauges), never classify traffic
    against the vanished ground truth — which would read every valid
    key as a false positive."""
    import attendance_tpu.obs.audit as audit_mod

    monkeypatch.setattr(audit_mod, "SHADOW_CAP", 100)
    reg = Registry()
    aud = ShadowAuditor(reg, 1.0)
    aud.record_roster(np.arange(1_000, dtype=np.uint32))
    assert aud._overflow.value == 1
    aud.observe_fused_frame(np.arange(500, dtype=np.uint32),
                            np.zeros(500, dtype=np.int64))
    roster, negatives = aud.fused_probe_sets()
    assert len(roster) == 0 and len(negatives) == 0
    assert aud.fused_day_truth() == {}


def test_traffic_reservoir_freezes_at_cap(monkeypatch):
    """The traffic probe population freezes at the cap (one overflow
    count, no per-frame eviction) and keeps measuring over the frozen
    set."""
    import attendance_tpu.obs.audit as audit_mod

    monkeypatch.setattr(audit_mod, "SHADOW_CAP", 200)
    reg = Registry()
    aud = ShadowAuditor(reg, 1.0)
    aud.record_roster(np.arange(50, dtype=np.uint32))
    for lo in (0, 300, 600):
        aud.observe_fused_frame(
            np.arange(lo, lo + 300, dtype=np.uint32),
            np.zeros(300, dtype=np.int64))
    assert aud._overflow.value == 1  # once, not per frame
    roster, negatives = aud.fused_probe_sets()
    assert len(roster) == 50
    assert 0 < len(negatives) <= 300


def test_no_signal_is_not_a_breach(tmp_path):
    """A NaN gauge (no sampled negative query yet) must not burn
    budget: silence is absence of evidence, not failure."""
    t, eng, fpr, st = _engine(tmp_path)
    for i in range(30):
        eng.tick(now=float(i))  # gauge still 0.0 default... set NaN
    fpr.set(float("nan"))
    for i in range(30, 60):
        eng.tick(now=float(i))
    assert not st.firing


def test_throughput_and_quantile_slos(tmp_path):
    t = obs.enable(Config(flight_recorder=4))
    eng = SloEngine(t, ("throughput>=100", "dequeue_p99<=0.1"),
                    fast_s=4.0, slow_s=20.0,
                    path=str(tmp_path / "a.jsonl"))
    ev = t.registry.counter("attendance_events_total")
    h = t.stage("dequeue_wait")
    for i in range(30):
        ev.inc(10)  # 10 events/tick = 10/s < 100 floor -> breach
        h.observe(0.5)  # every fresh observation breaches the p99
        eng.tick(now=float(i))
    assert eng._state["throughput"].firing
    assert eng._state["dequeue_p99"].firing
    events = [json.loads(l) for l in
              (tmp_path / "a.jsonl").read_text().splitlines()]
    assert {e["slo"] for e in events} == {"throughput", "dequeue_p99"}


def test_parse_slo_specs():
    s = parse_slo("fpr<=0.02")
    assert (s.name, s.op, s.threshold) == ("bloom_measured_fpr", "<=",
                                           0.02)
    s = parse_slo("throughput>=1e6")
    assert s.kind == "rate" and s.threshold == 1e6
    s = parse_slo("device_p95<=0.25")
    assert s.kind == "quantile" and s.quantile == 0.95
    assert s.label_filter == ("stage", "device_wait")
    with pytest.raises(ValueError):
        parse_slo("nonsense<=1")
    with pytest.raises(ValueError):
        parse_slo("fpr=0.01")
    # A user spec naming a default REPLACES it.
    slos = resolve_slos(["fpr<=0.5"])
    assert [s.threshold for s in slos
            if s.name == "bloom_measured_fpr"] == [0.5]
    assert len([s for s in slos if s.name == "bloom_false_negatives"]
               ) == 1


# -- quantiles ---------------------------------------------------------------

def test_histogram_quantile():
    reg = Registry()
    h = reg.histogram("h", scale=1.0)
    assert math.isnan(h.quantile(0.5))
    for v in (1, 1, 1, 1, 1, 1, 1, 1, 1, 100):
        h.observe(v)
    # p50 lands in bucket [1,2); p99 in [64,128) — the bucket holding
    # the 100 — and never claims a value below its lower bound.
    assert 1.0 <= h.quantile(0.50) <= 2.0
    assert 64.0 <= h.quantile(0.99) <= 128.0
    # Overflow honesty: a rank past the last finite bound answers +Inf.
    assert quantile_from_buckets([1], 2, 0.99, scale=1.0) == float(
        "inf")


def test_telemetry_verb_renders_quantiles(tmp_path, capsys):
    from attendance_tpu.cli import main
    from attendance_tpu.obs.exposition import render

    reg = Registry()
    h = reg.histogram("attendance_stage_latency_seconds",
                      stage="dequeue_wait")
    for _ in range(90):
        h.observe(0.001)
    for _ in range(10):
        h.observe(1.0)
    prom = tmp_path / "m.prom"
    prom.write_text("# scrape 1.0\n" + render(reg))
    main(["telemetry", str(prom)])
    out = capsys.readouterr().out
    assert "p50=" in out and "p95=" in out and "p99=" in out
    # p99 reflects the 1s outlier's bucket, not the 1ms mode.
    p99 = float(out.split("p99=")[1].split()[0])
    assert p99 > 0.5


# -- restore-then-scrape (health gauges survive restore) ---------------------

def test_store_health_gauges_survive_snapshot_restore(tmp_path):
    from attendance_tpu.utils.snapshot import (
        restore_sketch_store, snapshot_sketch_store)

    cfg = Config(sketch_backend="memory", metrics_port=-1)
    t = obs.enable(cfg)
    store = make_sketch_store(cfg)
    store.bf_add_many(cfg.bloom_filter_key,
                      np.arange(1_000, dtype=np.int64))
    store.pfadd_many(f"{cfg.hll_key_prefix}LECTURE_1",
                     np.arange(2_000, dtype=np.int64))
    before = t.registry.gauge("attendance_hll_estimate",
                              backend="memory").value
    assert before > 0
    path = tmp_path / "sketch.npz"
    snapshot_sketch_store(store, path)

    # Restore REPLACES the store's innards; a fresh process would also
    # build a brand-new store. Both must resume reporting.
    restored = make_sketch_store(cfg)
    restore_sketch_store(restored, path)
    del store  # the old generation is gone — gauges must not go stale
    g = t.registry.gauge("attendance_hll_estimate", backend="memory")
    assert g.value == pytest.approx(before)
    fill = t.registry.gauge("attendance_bloom_fill_fraction",
                            backend="memory").value
    assert 0 < fill < 1
    # The scrape surface renders them (no skipped-sample warnings).
    text = t.render()
    assert 'attendance_bloom_estimated_fpr{backend="memory"}' in text


def test_restored_tpu_store_resumes_reporting(tmp_path):
    from attendance_tpu.utils.snapshot import (
        restore_sketch_store, snapshot_sketch_store)

    cfg = Config(sketch_backend="tpu", metrics_port=-1)
    t = obs.enable(cfg)
    store = make_sketch_store(cfg)
    store.pfadd_many(f"{cfg.hll_key_prefix}LECTURE_1",
                     np.arange(500, dtype=np.int64))
    before = t.registry.gauge("attendance_hll_estimate",
                              backend="tpu").value
    path = tmp_path / "sketch.npz"
    snapshot_sketch_store(store, path)
    # Same store object, innards replaced — the weakref'd gauges must
    # read the RESTORED generation (the stale-closure regression).
    restore_sketch_store(store, path)
    g = t.registry.gauge("attendance_hll_estimate", backend="tpu")
    assert g.value == pytest.approx(before)


# -- doctor ------------------------------------------------------------------

def _doctor_artifacts(tmp_path, breached: bool):
    from attendance_tpu.obs.exposition import render

    reg = Registry()
    reg.gauge("attendance_bloom_measured_fpr").set(
        0.02 if breached else 0.004)
    reg.gauge("attendance_bloom_estimated_fpr").set(0.01)
    reg.counter("attendance_bloom_false_negatives_total")
    reg.gauge("attendance_hll_measured_rel_error",
              key="hll:unique:LECTURE_1").set(0.005)
    reg.gauge("attendance_slo_firing", slo="bloom_measured_fpr").set(
        1.0 if breached else 0.0)
    reg.gauge("attendance_slo_burn_rate", slo="bloom_measured_fpr",
              window="slow").set(20.0 if breached else 0.0)
    prom = tmp_path / "m.prom"
    prom.write_text("# scrape 1.0\n" + render(reg))

    alerts = tmp_path / "alerts.jsonl"
    if breached:
        alerts.write_text(json.dumps(
            {"schema": 1, "ts": 1.0, "slo": "bloom_measured_fpr",
             "state": "firing", "threshold": 0.01, "value": 0.02,
             "burn_fast": 75.0, "burn_slow": 20.0,
             "trace": "00000000deadbeef"}) + "\n")
    else:
        alerts.write_text("")

    flight = tmp_path / "flight.json"
    flight.write_text(json.dumps({
        "reason": "test", "pid": 1, "ring_size": 4, "total_records": 2,
        "records": [
            {"ts": 0.5, "events": 512, "trace": "00000000deadbeef"},
            {"ts": 1.0, "alert": "bloom_measured_fpr",
             "state": "firing", "trace": "00000000deadbeef"},
        ] if breached else [{"ts": 0.5, "events": 512}]}))
    return [str(prom), str(alerts), str(flight)]


def test_doctor_verdict_golden_and_exit_codes(tmp_path):
    from attendance_tpu.cli import main

    paths = _doctor_artifacts(tmp_path, breached=True)
    text, ok = doctor_report(paths)
    assert not ok
    assert text == GOLDEN.read_text()
    with pytest.raises(SystemExit) as e:
        main(["doctor"] + paths)
    assert e.value.code == 1


def test_doctor_passes_clean_artifacts(tmp_path, capsys):
    from attendance_tpu.cli import main

    paths = _doctor_artifacts(tmp_path, breached=False)
    text, ok = doctor_report(paths)
    assert ok
    main(["doctor"] + paths)  # returns without SystemExit
    assert "verdict: PASS" in capsys.readouterr().out


def test_doctor_unreadable_artifacts_exit_2(tmp_path):
    from attendance_tpu.cli import main

    with pytest.raises(SystemExit) as e:
        main(["doctor", str(tmp_path / "missing.prom")])
    assert e.value.code == 2
    bad = tmp_path / "bad.bin"
    bad.write_text("{not json")
    with pytest.raises(SystemExit) as e:
        main(["doctor", str(bad)])
    assert e.value.code == 2


def test_doctor_on_a_real_audited_run(tmp_path):
    """End to end: a clean memory-store run's own artifacts pass; the
    measured gauges land in the exposition the reporter wrote."""
    config = Config(bloom_filter_capacity=5_000, audit_sample=1.0,
                    metrics_prom=str(tmp_path / "m.prom"),
                    alert_log=str(tmp_path / "alerts.jsonl"),
                    flight_recorder=16)
    t = obs.enable(config)
    _fused_run(config)
    obs.disable()  # writes the final exposition block
    text, ok = doctor_report([str(tmp_path / "m.prom"),
                              str(tmp_path / "alerts.jsonl")])
    assert ok, text
    assert "bloom measured FPR" in text
