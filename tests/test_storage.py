"""Event-store semantics: PK upsert idempotence, ordered scans, save/load."""

from attendance_tpu.storage.memory_store import AttendanceRow, MemoryEventStore


def row(student=1, ts="2026-07-27T08:30:00", lecture="LECTURE_20260727",
        valid=True, etype="entry"):
    return AttendanceRow(student_id=student, timestamp=ts,
                         lecture_id=lecture, is_valid=valid,
                         event_type=etype)


def test_upsert_by_primary_key_is_idempotent():
    """Replayed batches overwrite in place (reference Cassandra PK
    semantics, attendance_processor.py:64-72; SURVEY.md §5)."""
    store = MemoryEventStore()
    store.insert_batch([row(), row(), row(student=2)])
    assert store.count() == 2
    store.insert_batch([row()])  # replay
    assert store.count() == 2


def test_scan_orders_by_clustering_key():
    store = MemoryEventStore()
    store.insert(row(student=2, ts="2026-07-27T10:00:00"))
    store.insert(row(student=1, ts="2026-07-27T08:00:00"))
    store.insert(row(student=3, ts="2026-07-27T08:00:00", lecture="OTHER"))
    scanned = store.scan_lecture("LECTURE_20260727")
    assert [(r.timestamp, r.student_id) for r in scanned] == [
        ("2026-07-27T08:00:00", 1), ("2026-07-27T10:00:00", 2)]


def test_distinct_lectures_and_scan_all():
    store = MemoryEventStore()
    store.insert(row(lecture="B"))
    store.insert(row(lecture="A", student=5))
    assert store.distinct_lecture_ids() == ["A", "B"]
    assert len(store.scan_all()) == 2


def test_save_load_roundtrip(tmp_path):
    store = MemoryEventStore()
    store.insert_batch([row(), row(student=2, valid=False, etype="exit")])
    path = tmp_path / "events.jsonl"
    store.save(path)
    restored = MemoryEventStore()
    assert restored.load(path) == 2
    assert restored.scan_all() == store.scan_all()
