"""Event-store semantics: PK upsert idempotence, ordered scans, save/load."""

from attendance_tpu.storage.memory_store import AttendanceRow, MemoryEventStore


def row(student=1, ts="2026-07-27T08:30:00", lecture="LECTURE_20260727",
        valid=True, etype="entry"):
    return AttendanceRow(student_id=student, timestamp=ts,
                         lecture_id=lecture, is_valid=valid,
                         event_type=etype)


def test_upsert_by_primary_key_is_idempotent():
    """Replayed batches overwrite in place (reference Cassandra PK
    semantics, attendance_processor.py:64-72; SURVEY.md §5)."""
    store = MemoryEventStore()
    store.insert_batch([row(), row(), row(student=2)])
    assert store.count() == 2
    store.insert_batch([row()])  # replay
    assert store.count() == 2


def test_scan_orders_by_clustering_key():
    store = MemoryEventStore()
    store.insert(row(student=2, ts="2026-07-27T10:00:00"))
    store.insert(row(student=1, ts="2026-07-27T08:00:00"))
    store.insert(row(student=3, ts="2026-07-27T08:00:00", lecture="OTHER"))
    scanned = store.scan_lecture("LECTURE_20260727")
    assert [(r.timestamp, r.student_id) for r in scanned] == [
        ("2026-07-27T08:00:00", 1), ("2026-07-27T10:00:00", 2)]


def test_distinct_lectures_and_scan_all():
    store = MemoryEventStore()
    store.insert(row(lecture="B"))
    store.insert(row(lecture="A", student=5))
    assert store.distinct_lecture_ids() == ["A", "B"]
    assert len(store.scan_all()) == 2


def test_save_load_roundtrip(tmp_path):
    store = MemoryEventStore()
    store.insert_batch([row(), row(student=2, valid=False, etype="exit")])
    path = tmp_path / "events.jsonl"
    store.save(path)
    restored = MemoryEventStore()
    assert restored.load(path) == 2
    assert restored.scan_all() == store.scan_all()


def test_columnar_hashed_lecture_id_roundtrip():
    """Non-calendar lecture ids must survive the store's id round-trip:
    distinct_lecture_ids() output fed back into scan_lecture() returns
    the original records (reference analytics loop shape,
    attendance_analysis.py:22-39)."""
    import numpy as np
    from attendance_tpu.pipeline.events import (
        AttendanceEvent, _lecture_to_day)
    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    store = ColumnarEventStore()
    ev = AttendanceEvent(student_id=42, timestamp="2026-03-02T09:00:00",
                         lecture_id="PHYS101", is_valid=True,
                         event_type="entry")
    store.insert(ev)
    (lid,) = store.distinct_lecture_ids()
    got = store.scan_lecture(lid)
    assert len(got["student_id"]) == 1
    assert int(got["student_id"][0]) == 42
    # and the synthetic id parses to the same day code, stably
    assert _lecture_to_day(lid) == _lecture_to_day("PHYS101")
    assert np.asarray(got["lecture_day"])[0] == _lecture_to_day("PHYS101")


def test_columnar_compaction_cache_invalidation():
    """to_columns memoizes until the next write."""
    import numpy as np
    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    store = ColumnarEventStore()
    def block(sid):
        return {"student_id": np.array([sid], np.int64),
                "lecture_day": np.array([20260101], np.int64),
                "micros": np.array([sid], np.int64),
                "is_valid": np.array([True]),
                "event_type": np.array([0], np.int8)}
    store.insert_columns(block(1))
    a = store.to_columns()
    assert store.to_columns() is a  # memoized
    store.insert_columns(block(2))
    b = store.to_columns()
    assert b is not a and len(b["student_id"]) == 2
    store.truncate()
    assert len(store.to_columns()["student_id"]) == 0


def test_columnar_row_adapter_preserves_lecture_ids():
    """Ids inserted through the row adapter must round-trip verbatim so
    sketch keys derived from them (processor's 'hll:<lecture_id>')
    keep working with --storage-backend=columnar."""
    from attendance_tpu.pipeline.events import AttendanceEvent
    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    store = ColumnarEventStore()
    store.insert_batch([
        AttendanceEvent(1, "2026-03-02T09:00:00", "PHYS101", True,
                        "entry"),
        AttendanceEvent(2, "2026-03-02T09:00:00", "LECTURE_20260302",
                        True, "entry"),
    ])
    assert sorted(store.distinct_lecture_ids()) == [
        "LECTURE_20260302", "PHYS101"]


def test_native_dedup_matches_numpy_lexsort():
    """The native hash dedup and the numpy lexsort dedup must keep the
    exact same rows (last write per primary key, append order)."""
    import numpy as np
    import pytest

    from attendance_tpu.native import load as load_native
    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")
    rng = np.random.default_rng(17)
    n = 50_000
    cols = {
        "student_id": rng.integers(0, 500, n).astype(np.int64),
        "lecture_day": rng.integers(20260101, 20260104, n
                                    ).astype(np.int64),
        # few distinct micros -> heavy duplication
        "micros": rng.integers(0, 200, n).astype(np.int64) * 1_000_000,
    }
    native_keep = ColumnarEventStore._dedup_keep(cols)

    order = np.lexsort((np.arange(n), cols["student_id"],
                        cols["micros"], cols["lecture_day"]))
    day = cols["lecture_day"][order]
    mic = cols["micros"][order]
    sid = cols["student_id"][order]
    last = np.ones(n, bool)
    last[:-1] = ((day[1:] != day[:-1]) | (mic[1:] != mic[:-1])
                 | (sid[1:] != sid[:-1]))
    numpy_keep = np.sort(order[last])
    np.testing.assert_array_equal(np.asarray(native_keep, np.int64),
                                  numpy_keep)


def test_scan_student_access_pattern():
    """The per-student access pattern of the README-promised
    events_by_student_day table (SURVEY §0.3 item 3), on both
    in-process stores: every row of one student, nothing else."""
    from attendance_tpu.storage.columnar_store import ColumnarEventStore
    from attendance_tpu.storage.memory_store import (
        AttendanceRow, MemoryEventStore)

    def row(sid, lec, ts, valid):
        return AttendanceRow(student_id=sid, timestamp=ts,
                             lecture_id=lec, is_valid=valid,
                             event_type="entry")

    rows = [row(11, "LECTURE_20260101", "2026-01-01T09:00:00", True),
            row(12, "LECTURE_20260101", "2026-01-01T09:01:00", True),
            row(11, "LECTURE_20260102", "2026-01-02T09:00:00", False),
            row(13, "LECTURE_20260102", "2026-01-02T09:02:00", True)]

    mem = MemoryEventStore()
    mem.insert_batch(rows)
    got = mem.scan_student(11)
    assert [(r.lecture_id, r.is_valid) for r in got] == [
        ("LECTURE_20260101", True), ("LECTURE_20260102", False)]
    assert mem.scan_student(999) == []

    col = ColumnarEventStore()
    col.insert_batch(rows)
    cols = col.scan_student(11)
    assert sorted(cols["lecture_day"].tolist()) == [20260101, 20260102]
    assert len(cols["student_id"]) == 2
    assert len(col.scan_student(999)["student_id"]) == 0


def test_columnar_segment_snapshots_are_incremental(tmp_path):
    """save_segments writes ONLY blocks appended since the last call
    (the checkpoint-at-rate fix: the legacy save() rewrites the whole
    deduped store at every barrier), and load_segments reproduces the
    exact append stream including read-time dedup semantics."""
    import numpy as np

    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    def block(sids, day=20260101):
        n = len(sids)
        return {"student_id": np.asarray(sids, np.uint32),
                "lecture_day": np.full(n, day, np.uint32),
                "micros": np.arange(n, dtype=np.int64),
                "is_valid": np.ones(n, bool),
                "event_type": np.zeros(n, np.int8)}

    store = ColumnarEventStore()
    segs = tmp_path / "segs"
    store.insert_columns(block([1, 2, 3]))
    assert store.save_segments(segs) == 3
    assert store.save_segments(segs) == 0  # nothing new -> no write
    assert len(list(segs.glob("segment-*.npz"))) == 1
    store.insert_columns(block([4, 5], day=20260102))
    assert store.save_segments(segs) == 2  # only the new block
    assert len(list(segs.glob("segment-*.npz"))) == 2

    restored = ColumnarEventStore()
    assert restored.load_segments(segs) == 5
    a = store.to_dataframe().sort_values(["lecture_day", "student_id"])
    b = restored.to_dataframe().sort_values(["lecture_day", "student_id"])
    assert a.student_id.tolist() == b.student_id.tolist()
    # Restored blocks are already durable: the next save writes nothing.
    assert restored.save_segments(segs) == 0
    # New data after a restore lands in a fresh, non-colliding segment.
    restored.insert_columns(block([6]))
    assert restored.save_segments(segs) == 1
    assert len(list(segs.glob("segment-*.npz"))) == 3


def test_columnar_segments_survive_truncate_reuse(tmp_path):
    """A truncate (bench passes reuse one store) resets the watermark
    but keeps segment numbering monotonic, so one snapshot dir never
    sees a filename collision."""
    import numpy as np

    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    store = ColumnarEventStore()
    segs = tmp_path / "segs"
    store.insert_columns({
        "student_id": np.asarray([7], np.uint32),
        "lecture_day": np.asarray([20260101], np.uint32),
        "micros": np.asarray([0], np.int64),
        "is_valid": np.asarray([True]),
        "event_type": np.asarray([0], np.int8)})
    assert store.save_segments(segs) == 1
    store.truncate()
    store.insert_columns({
        "student_id": np.asarray([8, 9], np.uint32),
        "lecture_day": np.asarray([20260101, 20260101], np.uint32),
        "micros": np.asarray([1, 2], np.int64),
        "is_valid": np.asarray([True, True]),
        "event_type": np.asarray([0, 0], np.int8)})
    assert store.save_segments(segs) == 2
    names = sorted(p.name for p in segs.glob("segment-*.npz"))
    assert len(names) == len(set(names)) == 2


def test_columnar_segment_compaction(tmp_path):
    """compact_segments merges many cadence segments into one (atomic,
    numbered past the originals), deletes the originals, and a
    crash between merge-write and deletes only leaves duplicates that
    read-time dedup folds — content is identical either way."""
    import numpy as np

    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    def block(sids, day, mic0):
        n = len(sids)
        return {"student_id": np.asarray(sids, np.uint32),
                "lecture_day": np.full(n, day, np.uint32),
                "micros": np.arange(mic0, mic0 + n, dtype=np.int64),
                "is_valid": np.ones(n, bool),
                "event_type": np.zeros(n, np.int8)}

    store = ColumnarEventStore()
    segs = tmp_path / "segs"
    for i in range(10):
        store.insert_columns(block([i * 10 + 1, i * 10 + 2],
                                   20260101 + i % 3, i * 100))
        assert store.save_segments(segs) == 2
    assert len(list(segs.glob("segment-*.npz"))) == 10

    # Below min_segments: no-op.
    assert store.compact_segments(segs, min_segments=20) == 0
    assert len(list(segs.glob("segment-*.npz"))) == 10

    assert store.compact_segments(segs) == 10
    remaining = list(segs.glob("segment-*.npz"))
    assert len(remaining) == 1
    merged = ColumnarEventStore()
    assert merged.load_segments(segs) == 20
    a = store.to_dataframe().sort_values(["micros", "student_id"])
    b = merged.to_dataframe().sort_values(["micros", "student_id"])
    assert a.student_id.tolist() == b.student_id.tolist()

    # Post-compaction saves land in fresh, later-sorting segments.
    merged.insert_columns(block([999], 20260104, 99_999))
    assert merged.save_segments(segs) == 1
    names = sorted(p.name for p in segs.glob("segment-*.npz"))
    assert len(names) == 2 and names[-1] > remaining[0].name

    # Crash simulation: merged file written but originals NOT deleted
    # (duplicate content on disk) -> load folds via read-time dedup.
    dup_dir = tmp_path / "dup"
    store2 = ColumnarEventStore()
    store2.insert_columns(block([5, 6], 20260101, 0))
    store2.save_segments(dup_dir)
    # copy the segment alongside itself as a later "merged" twin
    src = next(dup_dir.glob("segment-*.npz"))
    (dup_dir / "segment-99999999.npz").write_bytes(src.read_bytes())
    loaded = ColumnarEventStore()
    loaded.load_segments(dup_dir)
    assert loaded.count() == 2  # deduped, not 4
    # ...and a subsequent compaction FOLDS the overlap on disk instead
    # of baking it in (the merge dedups with the read path's rule).
    assert ColumnarEventStore().compact_segments(dup_dir,
                                                min_segments=2) == 2
    refolded = ColumnarEventStore()
    assert refolded.load_segments(dup_dir) == 2  # rows, not 4


def test_restore_compacts_segments(tmp_path):
    """FusedPipeline.restore() compacts a many-segment snapshot dir
    BEFORE loading, so restore cost stays bounded across long
    checkpointed runs. Segments are produced deterministically via
    explicit sync snapshots (the async writer coalesces cadence
    barriers, which would make the count timing-dependent)."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import (
        EVENTS_SEGMENTS, FusedPipeline)
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    snap = tmp_path / "snap"
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory",
                    snapshot_dir=str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=4)
    num_events, batch = 10_240, 1_024
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=4_000, num_lectures=4,
                                     seed=53)
    frames = list(frames)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
        pipe.run(max_events=batch, idle_timeout_s=0.3)
        pipe.snapshot()  # one sync snapshot -> one segment per frame
    segs = snap / EVENTS_SEGMENTS
    n_before = len(list(segs.glob("segment-*.npz")))
    assert n_before >= 8  # the compaction threshold is genuinely hit

    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=4)
    assert len(list(segs.glob("segment-*.npz"))) == 1
    assert pipe2.store.count() == pipe.store.count()
    np.testing.assert_array_equal(
        pipe2.store.to_dataframe().sort_values(
            ["micros", "student_id"]).is_valid.to_numpy(bool),
        pipe.store.to_dataframe().sort_values(
            ["micros", "student_id"]).is_valid.to_numpy(bool))
