"""Multi-host (DCN) tests for parallel.multihost + the sharded engine.

Two layers:

* Single-process: the fallback paths and constraint validation, on the
  virtual-8-device conftest mesh.
* **Two real processes** (VERDICT r02 #2): a hermetic
  ``jax.distributed`` CPU cluster — two subprocesses, 4 virtual devices
  each, gloo collectives over localhost TCP — running the n_procs>1
  branch of ``make_multihost_mesh`` with dp spanning the process
  boundary. The workers and the in-process single-process reference
  execute the identical workload (tests/multihost_worker.py) and must
  agree bit-for-bit: preload's cross-process all-gather-OR, the
  per-step sp-AND, and the deferred-sync PFCOUNT's cross-process
  register pmax all actually run. This is the framework's analogue of
  the reference's competing consumers on one Pulsar Shared
  subscription (reference attendance_processor.py:30-34).
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import pytest

from attendance_tpu.parallel.multihost import (
    init_distributed, make_multihost_mesh)

_REPO = Path(__file__).resolve().parents[1]
_WORKER = Path(__file__).resolve().parent / "multihost_worker.py"


def test_init_distributed_is_noop_single_process():
    assert init_distributed() is False
    assert jax.process_count() == 1


def test_init_distributed_rejects_partial_args():
    with pytest.raises(ValueError):
        init_distributed(num_processes=2)


def test_make_multihost_mesh_single_process_fallback():
    mesh = make_multihost_mesh(num_shards=2, num_replicas=4)
    assert mesh.shape == {"dp": 4, "sp": 2}


def test_make_multihost_mesh_defaults_replicas_to_all_devices():
    mesh = make_multihost_mesh(num_shards=2)
    assert mesh.shape["sp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dcn_cluster_matches_single_process(tmp_path):
    """The deliverable: a 2-process cluster executes the workload and
    lands on exactly the single-process answer (state SHAs included)."""
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=str(_REPO))
    outs = [tmp_path / f"r{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(i), "2", str(port),
             str(outs[i])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            logs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process cluster timed out\n" + "\n".join(logs))
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"

    results = [json.loads(o.read_text()) for o in outs]
    for r in results:
        assert r["process_count"] == 2

    # Single-process reference: same workload, same (dp=2, sp=4) mesh
    # shape, on this process's virtual 8-device CPU backend.
    from multihost_worker import run_pipeline_workload, run_workload
    ref = run_workload(make_multihost_mesh(num_shards=4))
    ref.update(run_pipeline_workload(make_multihost_mesh(num_shards=4)))

    for r in results:
        for key in ("nvalid_total", "total", "counts", "exact",
                    "member_roster", "member_invalid", "bloom_sha",
                    "regs_sha", "valid_sha", "pipe_events",
                    "pipe_valid_sha", "pipe_counts",
                    "pipe_validity_counts"):
            assert r[key] == ref[key], (key, r[key], ref[key])

    # Sanity on the shared answer itself: complete roster membership
    # (no false negatives), FPR within budget, PFCOUNTs near exact.
    assert ref["member_roster"] == 512
    assert ref["member_invalid"] <= 512 * 0.03
    for est, exact in zip(ref["counts"], ref["exact"]):
        assert abs(est - exact) / exact < 0.02
