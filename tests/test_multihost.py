"""Multi-host (DCN) tests for parallel.multihost + the sharded engine.

Two layers:

* Single-process: the fallback paths and constraint validation, on the
  virtual-8-device conftest mesh.
* **Two real processes** (VERDICT r02 #2): a hermetic
  ``jax.distributed`` CPU cluster — two subprocesses, 4 virtual devices
  each, gloo collectives over localhost TCP — running the n_procs>1
  branch of ``make_multihost_mesh`` with dp spanning the process
  boundary. The workers and the in-process single-process reference
  execute the identical workload (tests/multihost_worker.py) and must
  agree bit-for-bit: preload's cross-process all-gather-OR, the
  per-step sp-AND, and the deferred-sync PFCOUNT's cross-process
  register pmax all actually run. This is the framework's analogue of
  the reference's competing consumers on one Pulsar Shared
  subscription (reference attendance_processor.py:30-34).
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import pytest

from attendance_tpu.parallel.multihost import (
    init_distributed, make_multihost_mesh)

_REPO = Path(__file__).resolve().parents[1]
_WORKER = Path(__file__).resolve().parent / "multihost_worker.py"


def test_init_distributed_is_noop_single_process():
    assert init_distributed() is False
    assert jax.process_count() == 1


def test_init_distributed_rejects_partial_args():
    with pytest.raises(ValueError):
        init_distributed(num_processes=2)


def test_make_multihost_mesh_single_process_fallback():
    mesh = make_multihost_mesh(num_shards=2, num_replicas=4)
    assert mesh.shape == {"dp": 4, "sp": 2}


def test_make_multihost_mesh_defaults_replicas_to_all_devices():
    mesh = make_multihost_mesh(num_shards=2)
    assert mesh.shape["sp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# The installed jaxlib may predate cross-process collectives on the
# CPU backend ("Multiprocess computations aren't implemented on the
# CPU backend"). That is a platform capability gap, not a framework
# bug: detect it from the worker's own failure output and skip, the
# same policy as the "no C toolchain" skips.
_NO_MP_CPU = "Multiprocess computations aren't implemented"


def _skip_if_unsupported(logs) -> None:
    if any(_NO_MP_CPU in log for log in logs if log):
        pytest.skip("this jaxlib's CPU backend lacks multi-process "
                    "collectives (gloo DCN path unavailable)")


def test_two_process_crash_snapshot_restore(tmp_path):
    """VERDICT r04 #5: snapshot mid-run on the 2-process DCN cluster,
    SIGKILL both processes (a real crash — no teardown), then restore
    on a fresh SINGLE-process mesh of a different shape and replay the
    unacked second half of the stream. Counters, HLL counts, and the
    store must land exactly on the no-crash oracle."""
    import signal
    import time as _time

    import numpy as np

    port = _free_port()
    env = dict(os.environ, PYTHONPATH=str(_REPO))
    snap = tmp_path / "snap"
    outs = [tmp_path / f"c{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(i), "2", str(port),
             str(outs[i]), str(snap)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    try:
        # Wait until both workers report the mid-run snapshot barriers
        # completed (their out JSON exists), then SIGKILL — worker 1
        # first (the "crashed" competitor), then worker 0 (the snapshot
        # writer; its files survive it).
        deadline = _time.monotonic() + 420
        while not all(o.exists() for o in outs):
            if _time.monotonic() > deadline:
                for p in procs:
                    p.kill()
                logs = [p.communicate()[0] for p in procs]
                pytest.fail("crash workers timed out\n" + "\n".join(
                    log[-4000:] for log in logs))
            if any(p.poll() not in (None, -signal.SIGKILL)
                   for p in procs):
                logs = [p.communicate()[0] for p in procs]
                _skip_if_unsupported(logs)
                pytest.fail("crash worker exited early\n" + "\n".join(
                    log[-4000:] for log in logs))
            _time.sleep(0.2)
        _time.sleep(0.3)  # let the final JSON writes hit the disk
        procs[1].send_signal(signal.SIGKILL)
        procs[0].send_signal(signal.SIGKILL)
    finally:
        for p in procs:
            p.kill()
            p.wait()

    results = [json.loads(o.read_text()) for o in outs]
    for r in results:
        assert r["process_count"] == 2
        assert r["crash_events"] == 8_192
    # Only process 0 writes the shared dir: one sketch snapshot, plus
    # event segments from the mid-run barriers.
    from attendance_tpu.pipeline.fast_path import (
        EVENTS_SEGMENTS, SKETCH_SNAPSHOT)
    assert (snap / SKETCH_SNAPSHOT).exists()
    assert list((snap / EVENTS_SEGMENTS).glob("segment-*.npz"))

    # Restore onto a DIFFERENT single-process mesh shape and replay the
    # unacked second half (the broker died with the workers; in the
    # reference deployment Pulsar would redeliver exactly these).
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    num_events, batch = 16_384, 2_048
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=8_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=93)
    frames = list(frames)

    config = Config(bloom_filter_capacity=20_000,
                    transport_backend="memory",
                    num_shards=2, num_replicas=4, wire_format="word",
                    snapshot_dir=str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    assert pipe.metrics.events == 0  # events counter is per-process
    assert pipe.store.count() > 0  # restored store content
    producer = client.create_producer(config.pulsar_topic)
    for f in frames[num_events // 2 // batch:]:
        producer.send(f)
    pipe.run(max_events=num_events // 2, idle_timeout_s=1.0)

    # No-crash oracle: same stream end to end on a fresh pipeline.
    oracle_client = MemoryClient(MemoryBroker())
    oracle = FusedPipeline(
        Config(bloom_filter_capacity=20_000,
               transport_backend="memory", num_shards=2,
               num_replicas=4, wire_format="word"),
        client=oracle_client, num_banks=8)
    oracle.preload(roster)
    oprod = oracle_client.create_producer(config.pulsar_topic)
    for f in frames:
        oprod.send(f)
    oracle.run(max_events=num_events, idle_timeout_s=1.0)

    # Counters: crash-half (restored) + replay-half == oracle totals.
    assert tuple(pipe.validity_counts()) == \
        tuple(oracle.validity_counts())
    # HLL counts per lecture day: register max is order/merge-invariant,
    # so restored+resumed must equal the uninterrupted run exactly.
    assert pipe.lecture_days() == oracle.lecture_days()
    for day in oracle.lecture_days():
        assert pipe.count(day) == oracle.count(day)
    # Store: deduped content identical (the replay path may append
    # duplicates of rows already snapshotted; last-write-wins dedup
    # folds them exactly like Cassandra upsert would).
    a = pipe.store.to_dataframe().sort_values(
        ["micros", "student_id"]).reset_index(drop=True)
    b = oracle.store.to_dataframe().sort_values(
        ["micros", "student_id"]).reset_index(drop=True)
    assert len(a) == len(b)
    np.testing.assert_array_equal(a.is_valid.to_numpy(bool),
                                  b.is_valid.to_numpy(bool))
    np.testing.assert_array_equal(a.student_id.to_numpy(np.uint32),
                                  b.student_id.to_numpy(np.uint32))


def test_two_process_dcn_cluster_matches_single_process(tmp_path):
    """The deliverable: a 2-process cluster executes the workload and
    lands on exactly the single-process answer (state SHAs included)."""
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=str(_REPO))
    outs = [tmp_path / f"r{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(i), "2", str(port),
             str(outs[i])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            logs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process cluster timed out\n" + "\n".join(logs))
    _skip_if_unsupported(logs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"

    results = [json.loads(o.read_text()) for o in outs]
    for r in results:
        assert r["process_count"] == 2

    # Single-process reference: same workload, same (dp=2, sp=4) mesh
    # shape, on this process's virtual 8-device CPU backend.
    from multihost_worker import run_pipeline_workload, run_workload
    ref = run_workload(make_multihost_mesh(num_shards=4))
    ref.update(run_pipeline_workload(make_multihost_mesh(num_shards=4)))

    for r in results:
        for key in ("nvalid_total", "total", "counts", "exact",
                    "member_roster", "member_invalid", "bloom_sha",
                    "regs_sha", "valid_sha", "pipe_events",
                    "pipe_valid_sha", "pipe_counts",
                    "pipe_validity_counts"):
            assert r[key] == ref[key], (key, r[key], ref[key])

    # Sanity on the shared answer itself: complete roster membership
    # (no false negatives), FPR within budget, PFCOUNTs near exact.
    assert ref["member_roster"] == 512
    assert ref["member_invalid"] <= 512 * 0.03
    for est, exact in zip(ref["counts"], ref["exact"]):
        assert abs(est - exact) / exact < 0.02
