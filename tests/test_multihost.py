"""Multi-host mesh helper (parallel.multihost) — single-process paths.

A real multi-process DCN run needs a pod; these tests pin down the
single-process fallbacks and the constraint validation, and the
virtual-8-device conftest mesh exercises the same (dp, sp) axis layout
the multi-host path produces.
"""

import jax
import pytest

from attendance_tpu.parallel.multihost import (
    init_distributed, make_multihost_mesh)


def test_init_distributed_is_noop_single_process():
    assert init_distributed() is False
    assert jax.process_count() == 1


def test_init_distributed_rejects_partial_args():
    with pytest.raises(ValueError):
        init_distributed(num_processes=2)


def test_make_multihost_mesh_single_process_fallback():
    mesh = make_multihost_mesh(num_shards=2, num_replicas=4)
    assert mesh.shape == {"dp": 4, "sp": 2}


def test_make_multihost_mesh_defaults_replicas_to_all_devices():
    mesh = make_multihost_mesh(num_shards=2)
    assert mesh.shape["sp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2
