"""Hermetic coverage of the import-gated service adapters (VERDICT r02 #6).

None of redis-py / cassandra-driver / pulsar-client exist in this
environment, so the three adapters (`sketch/redis_store.py`,
`storage/cassandra_store.py`, `transport/pulsar_client.py`) shipped
with zero executed lines — a typo in the CQL or a wrong pipeline call
would ship green. These tests inject faithful fake client modules into
``sys.modules`` and execute every adapter line against them:

* fake ``redis`` — a client whose server side IS RedisSimSketchStore
  (Redis's actual algorithms), with a command-recording pipeline();
  drives the whole redis plumbing of the parity harness
  (check_redis + run_redis_parity) hermetically.
* fake ``cassandra`` — a session executing the adapter's exact CQL
  against an in-memory table with the reference's primary-key-upsert
  semantics; DDL/INSERT/scan shapes pinned against reference
  attendance_processor.py:56-72,116-124 and attendance_analysis.py:22-39.
* fake ``pulsar`` — Client/ConsumerType backed by the memory broker;
  pins the Shared-subscription default (reference
  attendance_processor.py:30-34) and runs a real FusedPipeline over the
  adapter end to end.
"""

import importlib
import re
import sys
import types
from datetime import datetime

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.sketch.redis_sim import RedisSimSketchStore
from attendance_tpu.storage.memory_store import AttendanceRow


# ---------------------------------------------------------------------------
# Fake redis-py
# ---------------------------------------------------------------------------

class _FakeRedisResponseError(Exception):
    pass


class _FakePipeline:
    """Records (command, args) like redis-py's pipeline, executing them
    against the sim server only at execute() — so the adapter's
    batching/chunking behavior is what's exercised, not bypassed."""

    def __init__(self, server):
        self._server = server
        self.commands = []

    def execute_command(self, *args):
        self.commands.append(args)
        return self

    def pfadd(self, key, *members):
        self.commands.append(("PFADD", key, *members))
        return self

    def execute(self):
        out = []
        for args in self.commands:
            try:
                out.append(self._server.execute_command(*args))
            except Exception as e:  # sim facade error -> redis error
                raise _FakeRedisResponseError(str(e)) from e
        self.commands = []
        return out


class _FakeRedis:
    """redis.Redis stand-in; the 'server' is a RedisSimSketchStore
    shared by every connection to the same (host, port)."""

    servers = {}

    def __init__(self, host="localhost", port=6379, decode_responses=False,
                 socket_connect_timeout=None, socket_timeout=None):
        key = (host, int(port))
        if key not in self.servers:
            self.servers[key] = RedisSimSketchStore(
                Config(sketch_backend="redis-sim"))
        self._server = self.servers[key]
        self.pipelines = []

    def ping(self):
        return True

    def execute_command(self, *args):
        try:
            return self._server.execute_command(*args)
        except Exception as e:
            raise _FakeRedisResponseError(str(e)) from e

    def pfadd(self, key, *members):
        return self._server.pfadd(str(key), *members)

    def pfcount(self, *keys):
        return self._server.pfcount(*[str(k) for k in keys])

    def pipeline(self):
        p = _FakePipeline(self._server)
        self.pipelines.append(p)
        return p

    def delete(self, *keys):
        n = 0
        for k in keys:
            n += int(self._server._blooms.pop(str(k), None) is not None)
            n += int(self._server._hlls.pop(str(k), None) is not None)
        return n

    def flushall(self):
        self._server.flush()

    def close(self):
        pass


def _fake_redis_module():
    mod = types.ModuleType("redis")
    mod.Redis = _FakeRedis
    exc = types.ModuleType("redis.exceptions")
    exc.ResponseError = _FakeRedisResponseError
    mod.exceptions = exc
    return mod


@pytest.fixture
def redis_store_cls(monkeypatch):
    """RedisSketchStore bound to the fake redis module (reloaded so the
    module-level import gate sees it); restores the pristine module
    state afterwards."""
    _FakeRedis.servers = {}
    monkeypatch.setitem(sys.modules, "redis", _fake_redis_module())
    import attendance_tpu.sketch.redis_store as rs
    importlib.reload(rs)
    assert rs.HAVE_REDIS
    yield rs.RedisSketchStore
    monkeypatch.delitem(sys.modules, "redis")
    importlib.reload(rs)


class TestRedisAdapter:
    def test_full_surface_and_pipeline_chunking(self, redis_store_cls):
        store = redis_store_cls(Config(sketch_backend="redis"))
        # Bootstrap shapes (reference attendance_processor.py:74-92).
        assert store.execute_command("BF.EXISTS", "bf", "test") == 0
        store.bf_reserve("bf", 0.01, 5000)
        from attendance_tpu.sketch.base import ResponseError
        with pytest.raises(ResponseError):  # translated exception type
            store.bf_reserve("bf", 0.01, 5000)
        roster = np.arange(10_000, 12_000, dtype=np.uint32)
        added = store.bf_add_many("bf", roster)
        assert added.sum() == len(roster)
        # The adapter chunks BF.MADD at 512 members through ONE pipeline.
        pipe = store.client.pipelines[-1]
        assert pipe.commands == []  # drained by execute()
        exists = store.bf_exists_many("bf", roster)
        assert exists.all()
        assert not store.bf_exists_many(
            "bf", np.arange(500_000, 500_200, dtype=np.uint32)).any()
        # HLL surface incl. masked bulk adds.
        assert store.pfadd("h", 1, 2, 3) == 1
        mask = np.zeros(len(roster), dtype=bool)
        mask[:100] = True
        store.pfadd_many("h", roster, mask=mask)
        c = store.pfcount("h")
        assert abs(c - 103) <= 3
        store.flush()
        assert store.pfcount("h") == 0
        store.close()

    def test_parity_harness_redis_plumbing(self, redis_store_cls):
        """check_redis + run_redis_parity end to end against the fake
        server — every line of the gated parity path executes."""
        from attendance_tpu.parity import check_redis, run_redis_parity

        config = Config(sketch_backend="redis")
        check_redis(config)  # ping + BF.RESERVE probe + delete
        report = run_redis_parity(config, num_events=4000,
                                  roster_size=1200, num_lectures=2,
                                  seed=9)
        assert report.ok, report.summary()

    def test_check_redis_reports_missing_module_cleanly(self, monkeypatch):
        from attendance_tpu.parity import RedisUnavailable, check_redis
        monkeypatch.setitem(sys.modules, "redis", None)
        with pytest.raises(RedisUnavailable):
            check_redis(Config())


# ---------------------------------------------------------------------------
# Fake cassandra-driver
# ---------------------------------------------------------------------------

class _FakeResultSet(list):
    def one(self):
        return self[0]


class _FakePrepared:
    def __init__(self, cql):
        self.cql = cql


class _FakeFuture:
    def __init__(self, fn):
        self._fn = fn
        self._done = False

    def result(self):
        if not self._done:
            self._fn()
            self._done = True


class _Row:
    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __getitem__(self, i):  # COUNT(*) rows are indexed positionally
        return list(self.__dict__.values())[i]


class _FakeSession:
    """Executes exactly the CQL shapes the adapter issues, with the
    reference table's primary-key-upsert semantics
    (PRIMARY KEY ((lecture_id), timestamp, student_id))."""

    def __init__(self):
        self.keyspaces = set()
        self.keyspace = None
        self.tables = set()
        self.rows = {}  # (lecture_id, ts, student_id) -> is_valid
        self.ddl = []

    def set_keyspace(self, ks):
        assert ks in self.keyspaces, f"keyspace {ks} does not exist"
        self.keyspace = ks

    def prepare(self, cql):
        assert "INSERT INTO attendance" in cql and cql.count("?") == 4
        return _FakePrepared(cql)

    def execute_async(self, stmt, params):
        assert isinstance(stmt, _FakePrepared)
        student_id, lecture_id, ts, is_valid = params
        assert isinstance(ts, datetime)

        def apply():
            self.rows[(lecture_id, ts, int(student_id))] = bool(is_valid)
        return _FakeFuture(apply)

    def execute(self, query, params=None):
        q = " ".join(query.split())
        if q.startswith("CREATE KEYSPACE IF NOT EXISTS"):
            self.ddl.append(q)
            self.keyspaces.add(q.split()[5])  # CREATE KEYSPACE IF NOT EXISTS <name>
            return _FakeResultSet()
        if q.startswith("CREATE TABLE IF NOT EXISTS attendance"):
            self.ddl.append(q)
            assert self.keyspace, "table DDL before set_keyspace"
            self.tables.add("attendance")
            return _FakeResultSet()
        if q == "SELECT DISTINCT lecture_id FROM attendance":
            return _FakeResultSet(
                _Row(lecture_id=lec)
                for lec in {k[0] for k in self.rows})
        if q.startswith("SELECT student_id, lecture_id, timestamp, "
                        "is_valid FROM attendance WHERE lecture_id = %s "
                        "ALLOW FILTERING"):
            (lec,) = params
            keys = sorted((k for k in self.rows if k[0] == lec),
                          key=lambda k: (k[1], k[2]))  # clustering order
            return _FakeResultSet(
                _Row(student_id=k[2], lecture_id=k[0], timestamp=k[1],
                     is_valid=self.rows[k]) for k in keys)
        if q.startswith("SELECT student_id, lecture_id, timestamp, "
                        "is_valid FROM attendance WHERE student_id = %s "
                        "ALLOW FILTERING"):
            (sid,) = params
            keys = [k for k in self.rows if k[2] == int(sid)]
            return _FakeResultSet(
                _Row(student_id=k[2], lecture_id=k[0], timestamp=k[1],
                     is_valid=self.rows[k]) for k in keys)
        if q == "SELECT COUNT(*) FROM attendance":
            return _FakeResultSet([_Row(count=len(self.rows))])
        if q == "TRUNCATE attendance":
            self.rows.clear()
            return _FakeResultSet()
        raise AssertionError(f"unexpected CQL: {q!r}")


class _FakeCluster:
    last = None

    def __init__(self, hosts):
        assert isinstance(hosts, list)
        self.hosts = hosts
        self.session = _FakeSession()
        self.shut = False
        _FakeCluster.last = self

    def connect(self):
        return self.session

    def shutdown(self):
        self.shut = True


@pytest.fixture
def cassandra_store_cls(monkeypatch):
    mod = types.ModuleType("cassandra")
    cluster_mod = types.ModuleType("cassandra.cluster")
    cluster_mod.Cluster = _FakeCluster
    mod.cluster = cluster_mod
    monkeypatch.setitem(sys.modules, "cassandra", mod)
    monkeypatch.setitem(sys.modules, "cassandra.cluster", cluster_mod)
    import attendance_tpu.storage.cassandra_store as cs
    importlib.reload(cs)
    assert cs.HAVE_CASSANDRA
    yield cs.CassandraEventStore
    monkeypatch.delitem(sys.modules, "cassandra")
    monkeypatch.delitem(sys.modules, "cassandra.cluster")
    importlib.reload(cs)


class TestCassandraAdapter:
    def test_ddl_matches_reference_schema(self, cassandra_store_cls):
        from attendance_tpu.storage import make_event_store
        store = make_event_store(Config(storage_backend="cassandra"))
        session = _FakeCluster.last.session
        ks_ddl, table_ddl = session.ddl[0], session.ddl[1]
        # Reference DDL shapes (attendance_processor.py:56-72).
        assert "SimpleStrategy" in ks_ddl
        assert "'replication_factor': 1" in ks_ddl
        assert re.search(r"PRIMARY KEY \(\(lecture_id\), timestamp, "
                         r"student_id\)", table_ddl)
        for col in ("student_id int", "lecture_id text",
                    "timestamp timestamp", "is_valid boolean"):
            assert col in table_ddl
        store.close()
        assert _FakeCluster.last.shut

    def test_insert_scan_upsert_and_truncate(self, cassandra_store_cls):
        store = cassandra_store_cls(Config(storage_backend="cassandra"))

        def row(sid, lec, ts, valid):
            return AttendanceRow(student_id=sid, timestamp=ts,
                                 lecture_id=lec, is_valid=valid,
                                 event_type="entry")

        n = store.insert_batch([
            row(11, "L1", "2026-07-01T09:00:00", True),
            row(12, "L1", "2026-07-01T09:05:00", True),
            row(13, "L2", "2026-07-01T10:00:00", False),
        ])
        assert n == 3
        # Replaying the same primary key upserts (the reference's
        # idempotency under at-least-once redelivery,
        # attendance_processor.py:116-124): same row count, last write
        # wins on the non-key column.
        store.insert(row(11, "L1", "2026-07-01T09:00:00", False))
        assert store.count() == 3
        assert store.distinct_lecture_ids() == ["L1", "L2"]
        scan = store.scan_lecture("L1")
        assert [r.student_id for r in scan] == [11, 12]  # clustering order
        assert scan[0].is_valid is False  # upserted value
        assert scan[0].timestamp == "2026-07-01T09:00:00"
        assert scan[0].event_type == "entry"  # placeholder column
        assert len(store.scan_all()) == 3
        # >128 rows exercises the in-flight async INSERT window.
        store.insert_batch([
            row(1000 + i, "L3", f"2026-07-02T09:{i % 60:02d}:{i // 60:02d}",
                True) for i in range(300)])
        assert store.count() == 303
        store.truncate()
        assert store.count() == 0
        store.close()


# ---------------------------------------------------------------------------
# Fake pulsar-client
# ---------------------------------------------------------------------------

def _fake_pulsar_module():
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    mod = types.ModuleType("pulsar")

    class ConsumerType:
        Exclusive = "Exclusive"
        Shared = "Shared"
        Failover = "Failover"

    class Client:
        def __init__(self, service_url):
            self.service_url = service_url
            self._inner = MemoryClient(MemoryBroker())
            self.subscribed_types = []
            self.closed = False
            Client.last = self

        def create_producer(self, topic):
            return self._inner.create_producer(topic)

        def subscribe(self, topic, subscription_name, consumer_type=None):
            self.subscribed_types.append(consumer_type)
            return self._inner.subscribe(topic, subscription_name)

        def close(self):
            self.closed = True
            self._inner.close()

    mod.ConsumerType = ConsumerType
    mod.Client = Client
    return mod


@pytest.fixture
def pulsar_client_cls(monkeypatch):
    monkeypatch.setitem(sys.modules, "pulsar", _fake_pulsar_module())
    import attendance_tpu.transport.pulsar_client as pc
    importlib.reload(pc)
    assert pc.HAVE_PULSAR
    yield pc.PulsarClient
    monkeypatch.delitem(sys.modules, "pulsar")
    importlib.reload(pc)


class TestPulsarAdapter:
    def test_shared_subscription_default_and_forwarding(
            self, pulsar_client_cls):
        from attendance_tpu.transport import make_client

        client = make_client(Config(transport_backend="pulsar"))
        fake = sys.modules["pulsar"].Client.last
        assert fake.service_url == Config().pulsar_host
        prod = client.create_producer("t")
        cons = client.subscribe("t", "sub")
        # The reference's Shared subscription type is the default
        # (attendance_processor.py:30-34).
        assert fake.subscribed_types == ["Shared"]
        prod.send(b"hello")
        msg = cons.receive(timeout_millis=100)
        assert msg.data() == b"hello"
        cons.negative_acknowledge(msg)  # redelivery
        msg2 = cons.receive(timeout_millis=2000)
        assert msg2.data() == b"hello"
        cons.acknowledge(msg2)
        assert cons.backlog() == 0
        client.close()
        assert fake.closed

    def test_fused_pipeline_runs_over_the_pulsar_adapter(
            self, pulsar_client_cls):
        """The flagship pipeline end to end through the adapter: the
        same consume/validate/count/ack flow the reference runs against
        a real broker (attendance_processor.py:100-136)."""
        from attendance_tpu.pipeline.fast_path import FusedPipeline
        from attendance_tpu.pipeline.loadgen import generate_frames

        config = Config(transport_backend="pulsar",
                        bloom_filter_capacity=10_000)
        client = pulsar_client_cls(config.pulsar_host)
        pipe = FusedPipeline(config, client=client, num_banks=8)
        roster, frames = generate_frames(4096, 1024, roster_size=5000,
                                         num_lectures=4, seed=4)
        pipe.preload(roster)
        prod = client.create_producer(config.pulsar_topic)
        for f in frames:
            prod.send(f)
        pipe.run(max_events=4096, idle_timeout_s=0.3)
        assert pipe.metrics.events == 4096
        assert pipe.consumer.backlog() == 0
        days = pipe.lecture_days()
        assert days and all(pipe.count(d) > 0 for d in days)
        pipe.cleanup()
