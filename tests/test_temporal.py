"""Temporal sketch plane (attendance_tpu/temporal): bucket-key
encoding, the watermark reorder stage, the bucket ring's
rotation/eviction bookkeeping, end-to-end order-independence of the
windowed HLL estimates (disordered stream == in-order oracle whenever
disorder <= allowed lateness), late-event side-channeling, chain
persistence/restore of bucket state, the window query verbs on every
serving surface, the doctor gate, and the loadgen disorder knobs.
"""

import json
import urllib.request

import numpy as np
import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.pipeline.events import decode_planar_batch
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import (
    apply_disorder, frame_from_columns, generate_frames)
from attendance_tpu.temporal.buckets import (
    BUCKET_KEY_BASE, MAX_PERIOD, bucket_key, bucket_keys,
    decode_bucket_key, is_bucket_key, period_micros)
from attendance_tpu.temporal.plane import TemporalPlane
from attendance_tpu.temporal.reorder import ReorderStage
from attendance_tpu.temporal.windows import BucketRing
from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient)

N_EVENTS, BATCH = 8_192, 512


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.disable()
    yield
    obs.disable()


def _tcfg(**kw):
    base = dict(bloom_filter_capacity=50_000,
                transport_backend="memory",
                temporal_period_s=2.0, allowed_lateness_s=1.6,
                temporal_ring_banks=64)
    base.update(kw)
    return Config(**base).validate()


def _run_pipe(config, frames, roster, num_banks=16, max_events=None):
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=num_banks)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=max_events or N_EVENTS, idle_timeout_s=0.6)
    return pipe


def _disordered_stream(seed=7, disorder=0.3, late_max_s=0.8,
                       n=N_EVENTS):
    roster, frames = generate_frames(
        n, BATCH, roster_size=5_000, num_lectures=4, seed=seed,
        disorder_frac=disorder, late_max_s=late_max_s, ordered=True)
    return roster, list(frames)


def _inorder_arrival(frames):
    """The SAME events re-framed in event-time arrival order — the
    in-order oracle stream for order-independence assertions."""
    cols = [decode_planar_batch(f) for f in frames]
    cat = {k: np.concatenate([c[k] for c in cols]) for k in cols[0]}
    order = np.argsort(cat["micros"], kind="stable")
    cat = {k: v[order] for k, v in cat.items()}
    n = len(cat["micros"])
    return [frame_from_columns({k: v[i:i + BATCH]
                                for k, v in cat.items()})
            for i in range(0, n, BATCH)]


# -- bucket keys --------------------------------------------------------------

def test_bucket_key_roundtrip_and_ordering():
    for day, period in [(0, 0), (20_260_701, 12_345),
                        (167_000_000, MAX_PERIOD)]:
        key = bucket_key(day, period)
        assert is_bucket_key(key)
        assert decode_bucket_key(key) == (day, period)
        assert key < (1 << 63)  # int64-safe for manifests/serve
    # Plain days are never bucket keys, in either direction.
    assert not is_bucket_key(20_260_701)
    with pytest.raises(ValueError):
        decode_bucket_key(20_260_701)
    with pytest.raises(ValueError):
        bucket_key(1 << 28, 0)
    with pytest.raises(ValueError):
        bucket_key(0, MAX_PERIOD + 1)
    keys = bucket_keys(np.array([1, 2], np.int64),
                       np.array([3, 3], np.int64))
    assert [decode_bucket_key(int(k)) for k in keys] == [(1, 3), (2, 3)]


def test_period_micros_validation():
    assert period_micros(2.0) == 2_000_000
    with pytest.raises(ValueError):
        period_micros(0.5)  # sub-second periods overflow the field


# -- reorder stage ------------------------------------------------------------

def _cols(micros, sid=None, day=20_260_701, etype=0):
    micros = np.asarray(micros, np.int64)
    n = len(micros)
    return {
        "student_id": (np.asarray(sid, np.uint32) if sid is not None
                       else np.arange(n, dtype=np.uint32) + 10_000),
        "lecture_day": np.full(n, day, np.uint32),
        "micros": micros,
        "event_type": np.full(n, etype, np.int8),
    }


def test_reorder_releases_in_event_time_order():
    rng = np.random.default_rng(3)
    stage = ReorderStage(lateness_us=500_000)
    base = 1_000_000_000
    micros = base + np.cumsum(rng.integers(1, 2_000, 4_000))
    shuffled = apply_disorder(micros, rng, 0.4, 0.3)
    released = []
    for i in range(0, 4_000, 500):
        out = stage.offer(_cols(shuffled[i:i + 500]))
        if out is not None:
            released.append(out["micros"])
    out = stage.flush()
    if out is not None:
        released.append(out["micros"])
    got = np.concatenate(released)
    assert len(got) == 4_000, "reorder lost or duplicated events"
    # Each release block is internally sorted, and (disorder <=
    # lateness) the whole released stream is globally sorted.
    assert (np.diff(got) >= 0).all()
    assert sorted(got.tolist()) == sorted(shuffled.tolist())


def test_reorder_flags_stragglers_late():
    stage = ReorderStage(lateness_us=100)
    stage.offer(_cols([1_000_000]))
    out = stage.offer(_cols([500, 2_000_000]))  # 500 is WAY late
    assert out is not None
    late = dict(zip(out["micros"].tolist(), out["late"].tolist()))
    assert late[500] is True or late[500] == True  # noqa: E712
    assert stage.late_released_total == 1


def test_reorder_watermark_lag_and_idle():
    stage = ReorderStage(lateness_us=2_000_000, idle_s=0.0)
    assert np.isnan(stage.watermark_lag_s())
    stage.offer(_cols([10_000_000]))
    # Event-time trail (the lateness) plus the wall-clock stall term
    # (events ARE buffered) — a stalled stream's lag must GROW.
    lag0 = stage.watermark_lag_s()
    assert 2.0 <= lag0 < 3.0
    import time as _time
    _time.sleep(0.05)
    assert stage.watermark_lag_s() > lag0  # live signal, not constant
    assert stage.buffered == 1
    out = stage.flush()
    assert len(out["micros"]) == 1
    assert stage.effective_watermark_us == 10_000_000  # head, post-flush
    # Post-flush: nothing buffered, watermark at head -> lag ~ 0.
    assert stage.watermark_lag_s() == pytest.approx(0.0, abs=1e-6)


# -- bucket ring --------------------------------------------------------------

class _Alloc:
    def __init__(self):
        self.next = 0
        self.freed = []

    def alloc(self, key):
        b = self.next
        self.next += 1
        return b

    def free(self, keys, banks):
        self.freed.append((list(keys), list(banks)))


def test_ring_rotation_and_drop():
    a = _Alloc()
    ring = BucketRing(1_000_000, 8, a.alloc, a.free)
    days = np.array([1, 1], np.int64)
    banks, dropped, touched = ring.assign(days,
                                          np.array([100, 1_100_000]))
    assert dropped == 0 and (banks >= 0).all()
    assert sorted(decode_bucket_key(k)[1] for k in touched) == [0, 1]
    assert ring.open_buckets == 2
    assert ring.rotate(1_000_000) == 1  # period 0 closes
    # A late event for the rotated bucket drops; the open one folds.
    banks, dropped, touched = ring.assign(days,
                                          np.array([200, 1_200_000]))
    assert dropped == 1
    assert banks[0] == -1 and banks[1] >= 0
    assert [decode_bucket_key(k)[1] for k in touched] == [1]
    assert ring.rotations_total == 1


def test_ring_evicts_oldest_closed_only():
    a = _Alloc()
    ring = BucketRing(1_000_000, 2, a.alloc, a.free)
    ring.assign(np.array([1], np.int64), np.array([100]))
    ring.assign(np.array([1], np.int64), np.array([1_000_100]))
    ring.rotate(2_000_000)  # both closed
    ring.assign(np.array([1], np.int64), np.array([2_000_100]))
    assert ring.evictions_total == 1
    (keys, banks), = a.freed
    assert decode_bucket_key(keys[0])[1] == 0  # the OLDEST went
    # Freed bank is recycled by the pipeline's free list (stub here).
    assert len(ring) == 2


def test_ring_never_evicts_open_buckets():
    a = _Alloc()
    ring = BucketRing(1_000_000, 2, a.alloc, a.free)
    for p in range(4):  # 4 open buckets, capacity 2: over-commit
        ring.assign(np.array([1], np.int64),
                    np.array([p * 1_000_000 + 1]))
    assert ring.evictions_total == 0
    assert len(ring) == 4  # over capacity, loudly, but no data loss


def test_ring_restore_reseeds_buckets():
    a = _Alloc()
    ring = BucketRing(1_000_000, 8, a.alloc, a.free)
    bank_of = {bucket_key(1, 5): 3, 20_260_701: 0,
               bucket_key(2, 6): 4}
    assert ring.restore(bank_of) == 2  # plain day keys ignored
    assert ring.open_buckets == 2


# -- config -------------------------------------------------------------------

def test_config_validation():
    with pytest.raises(ValueError):
        Config(temporal_period_s=0.5).validate()
    with pytest.raises(ValueError):
        Config(temporal_period_s=2.0, num_shards=2).validate()
    with pytest.raises(ValueError):
        Config(temporal_ring_banks=1).validate()
    with pytest.raises(ValueError):
        Config(cms_topk=0).validate()
    _tcfg()  # the happy path


# -- end-to-end order independence -------------------------------------------

def test_disordered_stream_equals_inorder_oracle():
    """THE acceptance property: with disorder <= allowed lateness,
    the windowed estimates of a disordered stream equal the in-order
    oracle's exactly (same added sets -> same registers), the exact
    shadow agrees, nothing drops, and the day plane is untouched."""
    roster, frames = _disordered_stream()
    oracle_frames = _inorder_arrival(frames)
    results = []
    for stream in (oracle_frames, frames):
        pipe = _run_pipe(_tcfg(audit_sample=1.0,
                               metrics_port=-1), stream, roster)
        results.append((
            pipe.window_counts(), pipe._temporal.shadow_truth(),
            {int(d): pipe.count(int(d))
             for d in pipe.lecture_days()},
            pipe.temporal_stats()))
        pipe.cleanup()
        obs.disable()
    (wc0, sh0, days0, ts0), (wc1, sh1, days1, ts1) = results
    assert wc0 == wc1
    assert sh0 == sh1
    assert days0 == days1
    assert ts1["late_dropped"] == 0
    assert ts0["rotations"] > 0 and ts1["rotations"] > 0
    # Estimates track the exact shadow within the HLL error budget.
    errs = [abs(wc1[k] - t) / max(t, 1) for k, t in sh1.items()]
    assert max(errs) <= 0.05
    # Zero window false negatives: every shadow bucket is served.
    assert set(sh1) <= set(wc1)


def test_super_late_events_side_channel():
    """Events beyond any lateness budget (targeting long-rotated
    buckets) are DROPPED to the side channel — counted, sampled,
    never misbucketed. The windowed estimates are identical to a run
    WITHOUT the stragglers (a closed window's answer never changes
    after the fact), while the order-free day plane — where arrival
    order is irrelevant by construction — still counts them."""
    roster, frames = _disordered_stream(seed=9, disorder=0.0)
    # A tail frame re-sending the FIRST frame's (now ancient) events.
    cols = decode_planar_batch(frames[0])
    tail = {k: np.array(v[:64]) for k, v in cols.items()}
    with_tail = frames + [frame_from_columns(tail)]

    base = _run_pipe(_tcfg(), frames, roster)
    wc_base = base.window_counts()
    base.cleanup()

    pipe = _run_pipe(_tcfg(), with_tail, roster,
                     max_events=N_EVENTS + 64)
    ts = pipe.temporal_stats()
    assert ts["late_dropped"] >= 64
    assert pipe.window_counts() == wc_base  # no closed-window change
    # The day plane counted the tail's events (idempotent re-adds of
    # already-seen students: counts unchanged is ALSO correct — just
    # assert the day surface answered and is non-empty).
    assert pipe.lecture_days()
    pipe.cleanup()


def test_drop_sample_side_channel_contents():
    roster, frames = _disordered_stream(seed=5, disorder=0.0)
    cols = decode_planar_batch(frames[0])
    tail = {k: np.array(v[:8]) for k, v in cols.items()}
    frames = frames + [frame_from_columns(tail)]
    pipe = _run_pipe(_tcfg(), frames, roster, max_events=N_EVENTS + 8)
    sample = list(pipe._temporal.dropped_sample)
    assert len(sample) == 8  # exactly the tail, nothing else
    sids = {s for s, _, _ in sample}
    assert sids <= set(int(s) for s in cols["student_id"][:8])
    pipe.cleanup()


# -- persistence --------------------------------------------------------------

def test_bucket_state_persists_through_delta_chain(tmp_path):
    """Windowed state rides the PR 4 chain unchanged: a fresh
    pipeline restoring the chain answers identical window estimates,
    the ring re-seeds, and the bank allocator's free list recovers
    eviction holes."""
    cfg = _tcfg(temporal_ring_banks=8, snapshot_dir=str(tmp_path),
                snapshot_mode="delta", snapshot_every_batches=4)
    roster, frames = _disordered_stream(seed=7, disorder=0.0)
    pipe = _run_pipe(cfg, frames, roster)
    want_wc = pipe.window_counts()
    want_days = {int(d): pipe.count(int(d))
                 for d in pipe.lecture_days()}
    assert pipe.temporal_stats()["evictions"] > 0  # tiny ring
    pipe.snapshot()
    pipe.cleanup()

    pipe2 = FusedPipeline(cfg, client=MemoryClient(MemoryBroker()),
                          num_banks=16)
    assert pipe2.window_counts() == want_wc
    assert {int(d): pipe2.count(int(d))
            for d in pipe2.lecture_days()} == want_days
    assert pipe2.temporal_stats()["buckets"] == len(want_wc)
    used = set(pipe2._bank_of.values())
    assert set(pipe2._free_banks) == \
        set(range(pipe2._next_bank)) - used
    pipe2.cleanup()


# -- serving surfaces ---------------------------------------------------------

def _pipe_with_epoch():
    roster, frames = _disordered_stream(seed=7)
    pipe = _run_pipe(_tcfg(), frames, roster)
    pipe.publish_epoch()
    return pipe


def test_engine_window_verbs_match_pipeline():
    from attendance_tpu.serve.engine import QueryEngine

    pipe = _pipe_with_epoch()
    eng = QueryEngine(pipe.read_mirror)
    wocc = eng.window_occupancy()
    want = {decode_bucket_key(k): v
            for k, v in pipe.window_counts().items()}
    assert wocc == want
    # occupancy()/rate() stay day-only: no bucket keys leak through.
    assert all(not is_bucket_key(d) for d in eng.occupancy())
    # window_pfcount folds registers (merge-on-read): for a single
    # bucket it equals that bucket's estimate; for a range it is
    # bounded by the per-bucket sum and >= the max member.
    (day, period), est = next(iter(sorted(wocc.items())))
    assert eng.window_pfcount(day, period, period) == est
    periods = [p for (d, p) in wocc if d == day]
    whole = eng.window_pfcount(day)
    assert whole >= max(est for (d, _), est in wocc.items()
                        if d == day) * 0.95
    assert whole <= sum(est for (d, _), est in wocc.items()
                        if d == day) * 1.05
    series = eng.rate_series(day)
    assert set(series) == set(periods)
    assert all(0.0 <= r <= 1.5 for r in series.values())
    stats = eng.stats()
    assert stats["window_buckets"] == len(wocc)
    pipe.cleanup()


def test_window_rpc_roundtrip():
    from attendance_tpu.serve.engine import QueryEngine
    from attendance_tpu.serve.rpc import QueryClient, QueryServer

    pipe = _pipe_with_epoch()
    eng = QueryEngine(pipe.read_mirror)
    server = QueryServer(eng, port=0).start()
    client = QueryClient(server.address)
    try:
        assert client.window_occupancy() == eng.window_occupancy()
        (day, period) = next(iter(sorted(eng.window_occupancy())))
        assert client.window_pfcount(day, period, period) == \
            eng.window_pfcount(day, period, period)
        assert client.window_pfcount() == eng.window_pfcount()
        assert client.rate_series(day) == \
            pytest.approx(eng.rate_series(day))
    finally:
        client.close()
        server.stop()
        pipe.cleanup()


def test_window_http_routes():
    from attendance_tpu.serve import http as serve_http
    from attendance_tpu.serve.engine import QueryEngine

    telemetry = obs.enable(Config(metrics_port=-1))
    pipe = _pipe_with_epoch()
    eng = QueryEngine(pipe.read_mirror)
    serve_http.attach(telemetry._server, eng)
    port = telemetry.http_port
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return json.loads(r.read())

        wocc = get("/query/window_occupancy")
        assert wocc == {f"{d}:{p}": v for (d, p), v in
                        sorted(eng.window_occupancy().items())}
        (day, period) = next(iter(sorted(eng.window_occupancy())))
        doc = get(f"/query/window?day={day}&from={period}&to={period}")
        assert doc["unique"] == eng.window_pfcount(day, period, period)
        series = get(f"/query/rate_series?day={day}")
        assert series == {str(p): pytest.approx(r) for p, r in
                          eng.rate_series(day).items()}
        # POST batch dispatch reaches the window verbs too.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/query",
            data=json.dumps({"verb": "window_pfcount", "day": day,
                             "period_lo": period,
                             "period_hi": period}).encode(),
            method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["result"] == eng.window_pfcount(day, period, period)
    finally:
        serve_http.detach(telemetry._server)
        pipe.cleanup()


def test_restored_free_bank_reallocates_clean(tmp_path):
    """An evicted bucket's bank lands on the free list at restore,
    but the CHAIN still holds the dead bucket's registers (its live
    zeroing was never re-captured — the dirty mark died with it).
    Restore must zero hole rows before reuse, or a new key allocated
    into the hole scatter-maxes onto stale state and overcounts
    (review finding; the ONE path the persistence test missed)."""
    cfg = _tcfg(snapshot_dir=str(tmp_path), snapshot_mode="delta",
                snapshot_every_batches=4)
    roster, frames = _disordered_stream(seed=7, disorder=0.0)
    pipe = _run_pipe(cfg, frames, roster)
    # Deterministic hole: evict one well-fed bucket (its rows sit in
    # earlier deltas) AFTER the run's last capture, then publish one
    # more barrier so the final manifest drops the key WITHOUT ever
    # re-capturing the zeroed row — exactly the live-eviction state a
    # crash leaves on disk.
    ring = pipe._temporal.ring
    key = max(ring.buckets, key=lambda k: 0)  # any retained bucket
    bank = ring.buckets.pop(key)
    pipe._free_temporal_buckets([key], [bank])
    pipe._checkpoint_async(force=True)
    pipe._flush_snapshots()
    pipe.cleanup()

    pipe2 = FusedPipeline(cfg, client=MemoryClient(MemoryBroker()),
                          num_banks=16)
    assert bank in pipe2._free_banks, "no eviction hole restored"
    # A NEW lecture day allocated into a hole must count ONLY its own
    # students — 3 distinct swipes, not the dead bucket's hundreds.
    new_day = 20_991_231
    sids = np.array(sorted(roster)[:3], np.uint32)
    producer = pipe2.client.create_producer(cfg.pulsar_topic)
    producer.send(frame_from_columns({
        "student_id": sids,
        "lecture_day": np.full(3, new_day, np.uint32),
        "micros": np.array([10 ** 15] * 3, np.int64),
        "is_valid": np.ones(3, bool),
        "event_type": np.zeros(3, np.int8)}))
    holes = list(pipe2._free_banks)
    pipe2.run(max_events=3, idle_timeout_s=0.5)
    assert pipe2._bank_of[new_day] in holes  # really took the hole
    assert pipe2.count(new_day) == 3
    pipe2.cleanup()


def test_window_verbs_over_chain_reader(tmp_path):
    """The separate-process read replica answers the window verbs
    from the on-disk chain alone — the bucket map travels inside the
    manifest's bank_of, no live-ring state needed (and the chain
    reader int-normalizes the JSON-stringified keys)."""
    from attendance_tpu.serve.chain import ChainEpochSource
    from attendance_tpu.serve.engine import QueryEngine

    cfg = _tcfg(snapshot_dir=str(tmp_path), snapshot_mode="delta",
                snapshot_every_batches=4)
    roster, frames = _disordered_stream(seed=7, disorder=0.0)
    pipe = _run_pipe(cfg, frames, roster)
    want = {decode_bucket_key(k): v
            for k, v in pipe.window_counts().items()}
    want_days = {int(d): pipe.count(int(d))
                 for d in pipe.lecture_days()}
    pipe.snapshot()
    pipe.cleanup()

    source = ChainEpochSource(str(tmp_path)).start()
    try:
        eng = QueryEngine(source)
        assert eng.window_occupancy() == want
        assert {int(d): int(c) for d, c in eng.occupancy().items()} \
            == want_days
        day, period = next(iter(sorted(want)))
        assert eng.window_pfcount(day, period, period) == \
            want[(day, period)]
    finally:
        source.stop()


# -- observability / doctor ---------------------------------------------------

def test_metrics_and_doctor_rows(tmp_path):
    from attendance_tpu.obs.slo import doctor_report

    prom = tmp_path / "metrics.prom"
    roster, frames = _disordered_stream(seed=7)
    cols = decode_planar_batch(frames[0])
    tail = {k: np.array(v[:16]) for k, v in cols.items()}
    frames = frames + [frame_from_columns(tail)]
    pipe = _run_pipe(_tcfg(metrics_prom=str(prom),
                           metrics_interval_s=0.2), frames, roster,
                     max_events=N_EVENTS + 16)
    t = obs.get()
    t._reporter._write_block()
    text = prom.read_text()
    assert "attendance_watermark_lag_seconds" in text
    assert 'attendance_late_events_total{outcome="dropped"}' in text
    assert "attendance_window_rotations_total" in text
    pipe.cleanup()

    out, ok = doctor_report([str(prom)], watermark_lag_ceiling=10.0)
    assert ok and "watermark lag" in out
    # A breaching lag value must FAIL the gate (the live run's
    # end-of-run flush legitimately reads ~0, so gate a crafted
    # exposition carrying a stalled-stream lag).
    lagging = tmp_path / "lag.prom"
    lagging.write_text("attendance_watermark_lag_seconds 5.0\n")
    out, ok = doctor_report([str(lagging)], watermark_lag_ceiling=1.0)
    assert not ok
    out, ok = doctor_report([str(lagging)], watermark_lag_ceiling=10.0)
    assert ok
    # Vacuous-pass refusal: a ceiling over a non-temporal run fails.
    bare = tmp_path / "bare.prom"
    bare.write_text("attendance_events_total 5\n")
    out, ok = doctor_report([str(bare)], watermark_lag_ceiling=10.0)
    assert not ok


def test_watermark_lag_slo_alias():
    from attendance_tpu.obs.slo import parse_slo

    slo = parse_slo("watermark_lag<=3.5")
    assert slo.metric == "attendance_watermark_lag_seconds"
    assert slo.threshold == 3.5


# -- loadgen / generator knobs ------------------------------------------------

def test_loadgen_disorder_deterministic_and_bounded():
    _, f1 = _disordered_stream(seed=11)
    _, f2 = _disordered_stream(seed=11)
    assert [bytes(a) for a in f1] == [bytes(b) for b in f2]
    cols = [decode_planar_batch(f) for f in f1]
    micros = np.concatenate([c["micros"] for c in cols])
    # Disorder present, bounded by late_max_s against the running head.
    head = np.maximum.accumulate(micros)
    lag = head - micros
    assert (lag > 0).any()
    assert int(lag.max()) <= int(0.8 * 1e6) + 2_000_000  # + gap slack
    frac = float((lag > 0).mean())
    assert 0.1 < frac < 0.6  # ~0.3 requested


def test_generator_disorder_deterministic():
    from attendance_tpu.pipeline.generator import generate_student_data

    r1 = generate_student_data(num_students=40, num_invalid=5, seed=3,
                               disorder_frac=0.4, late_max_s=600)
    r2 = generate_student_data(num_students=40, num_invalid=5, seed=3,
                               disorder_frac=0.4, late_max_s=600)
    ts1 = [e.timestamp for e in r1.events]
    assert ts1 == [e.timestamp for e in r2.events]
    assert r1.message_count == r2.message_count
    # Emission is event-time sorted EXCEPT the displaced sample.
    in_order = generate_student_data(num_students=40, num_invalid=5,
                                     seed=3, disorder_frac=1e-9,
                                     late_max_s=0)
    assert sorted(ts1) == sorted(e.timestamp
                                 for e in in_order.events)
    assert ts1 != sorted(ts1)  # disorder actually happened


# -- transport ordering (the soak-found fix) ----------------------------------

def test_crash_takeover_requeues_at_head_in_order():
    """A dead consumer's unacked window must replay BEFORE the
    undelivered backlog, in publish order (the shm ring's
    resume-from-cursor semantics): tail requeue reordered delivery by
    the whole backlog length, which no event-time lateness budget can
    cover — the temporal soak caught redelivered events landing
    behind rotated buckets."""
    broker = MemoryBroker()
    client = MemoryClient(broker)
    consumer = client.subscribe("t", "s")
    producer = client.create_producer("t")
    for i in range(6):
        producer.send(bytes([i]))
    for _ in range(3):
        consumer.receive(timeout_millis=200)  # in-flight, unacked
    consumer.close()  # crash takeover: requeue
    c2 = client.subscribe("t", "s")
    order = []
    for _ in range(6):
        msg = c2.receive(timeout_millis=200)
        order.append(msg.data()[0])
        c2.acknowledge(msg)
    assert order == [0, 1, 2, 3, 4, 5]


# -- dwell pairing ------------------------------------------------------------

def test_dwell_pairing_matches_oracle():
    cfg = _tcfg()
    alloc = _Alloc()
    plane = TemporalPlane(cfg, alloc_bank=alloc.alloc,
                          free_buckets=alloc.free,
                          mark_dirty=lambda keys: None,
                          dispatch_add=lambda k, b: None)
    base = 1_000_000_000
    # student 1: entry@0s exit@40s; student 2 entry@10s exit@15s on a
    # different release block; student 3: exit with no entry.
    def frame(rows):
        sid, et, t = zip(*rows)
        return {"student_id": np.array(sid, np.uint32),
                "lecture_day": np.full(len(rows), 20_260_701,
                                       np.uint32),
                "micros": base + np.array(t, np.int64),
                "event_type": np.array(et, np.int8)}

    plane.observe_frame(frame([(1, 0, 0), (2, 0, 10_000_000),
                               (3, 1, 11_000_000)]))
    plane.observe_frame(frame([(2, 1, 15_000_000),
                               (1, 1, 40_000_000)]))
    plane.flush()
    assert plane.dwell_pairs_total == 2
    assert plane.dwell_unmatched_exits == 1
    assert plane.dwell_hist.sum() == 2
    # dwell 40s and 5s -> log2(us) buckets 25 and 22
    assert plane.dwell_hist[int(np.log2(40e6))] == 1
    assert plane.dwell_hist[int(np.log2(5e6))] == 1
