"""Incident plane tests (ISSUE 17): correlated breach detection,
evidence bundles, the rule-driven diagnosis table, ``doctor
--incident`` replay, the alert-log schema field, exemplars, and the
per-lane flight-recorder routing fix."""

import hashlib
import json

import numpy as np
import pytest

from attendance_tpu import chaos, obs
from attendance_tpu.config import Config
from attendance_tpu.obs.incident import (
    EVIDENCE_PARTS,
    IncidentEngine,
    diagnose,
    find_bundles,
    incident_report,
)
from attendance_tpu.obs.slo import ALERT_SCHEMA


@pytest.fixture(autouse=True)
def _clean_planes():
    chaos.disable()
    obs.disable()
    yield
    chaos.disable()
    obs.disable()


def _engine(tmp_path, **cfg_kw):
    """Telemetry + a stopped incident engine driven by manual ticks."""
    cfg_kw.setdefault("incident_dir", str(tmp_path / "incidents"))
    t = obs.enable(Config(**cfg_kw))
    eng = t.incidents
    assert isinstance(eng, IncidentEngine)
    eng.stop()  # tests drive tick() directly, like the SLO suite
    eng.dir.mkdir(parents=True, exist_ok=True)
    return t, eng


def _bundle_dirs(eng):
    return find_bundles(eng.dir)


# -- diagnosis signature table ----------------------------------------------

def test_diagnose_golden_table():
    """The spec's four composite signatures rank their named cause
    first, and every single condition maps to some rule (no
    undiagnosable lone signal)."""
    golden = [
        ({"circuit_open", "spill_growth", "slo_burn"}, "persist_sink_down"),
        ({"circuit_open", "spill_growth"}, "persist_sink_down"),
        ({"steady_recompiles"}, "shape_churn"),
        ({"steady_recompiles", "throughput_drop", "dispatch_gap"},
         "shape_churn"),
        ({"peer_down", "merge_lag"}, "dead_worker"),
        ({"peer_down"}, "dead_worker"),
        ({"throughput_drop", "stage_shift"}, "temporal_dispatch_pass"),
        ({"merge_lag"}, "fed_merge_backlog"),
        ({"read_staleness"}, "stale_reads"),
        ({"watermark_lag"}, "watermark_stall"),
        ({"lane_stall"}, "lane_stall"),
        ({"circuit_open"}, "sink_circuit_open"),
        ({"integrity_rejects"}, "wire_rot"),
        ({"slo_burn"}, "slo_burn"),
        ({"dispatch_gap"}, "dispatch_gap"),
    ]
    for conds, expected in golden:
        ranked = diagnose(conds)
        assert ranked, f"no diagnosis for {conds}"
        assert ranked[0]["rule"] == expected, (conds, ranked[0])
        # Scores are monotone non-increasing and every match lists
        # only conditions actually present.
        scores = [r["score"] for r in ranked]
        assert scores == sorted(scores, reverse=True)
        for r in ranked:
            assert set(r["matched"]) <= conds


def test_diagnose_specificity_beats_breadth():
    """persist_sink_down (two required conditions) outranks the broad
    sink_circuit_open rule when spill is actually growing."""
    ranked = diagnose({"circuit_open", "spill_growth"})
    names = [r["rule"] for r in ranked]
    assert names.index("persist_sink_down") < names.index("sink_circuit_open")


# -- open / clear hysteresis -------------------------------------------------

def test_incident_open_clear_hysteresis(tmp_path):
    t, eng = _engine(tmp_path, incident_clear_ticks=3)
    circuit = t.registry.gauge("attendance_circuit_state", sink="disk")

    assert eng.tick() is None  # warm-up, no conditions
    circuit.set(1.0)
    iid = eng.tick()  # breach visible -> opens within ONE tick
    assert iid is not None and iid.startswith("inc-")
    assert t.registry.gauge("attendance_incidents_open").read() == 1.0
    assert t.registry.counter("attendance_incidents_total",
                              rule="sink_circuit_open").value == 1

    circuit.set(0.0)
    assert eng.tick() == iid  # 1 clean tick: still open (hysteresis)
    assert eng.tick() == iid  # 2 clean ticks: still open
    assert eng.tick() is None  # 3rd clean tick: cleared
    assert t.registry.gauge("attendance_incidents_open").read() == 0.0

    [bundle] = _bundle_dirs(eng)
    rec = json.loads((bundle / "incident.json").read_text())
    assert rec["schema"] == ALERT_SCHEMA
    assert rec["kind"] == "incident"
    assert rec["id"] == iid
    assert rec["cleared_unix"] is not None
    assert rec["cleared_unix"] >= rec["opened_unix"]
    assert rec["conditions"] == ["circuit_open"]
    assert rec["diagnosis_top"] == "sink_circuit_open"


def test_secondary_conditions_never_open_alone(tmp_path):
    """throughput_drop / stage_shift corroborate but never page alone:
    a benign idle tail (rate collapses to zero after sustained load)
    must not open an undiagnosed incident."""
    t, eng = _engine(tmp_path)
    events = t.registry.counter("attendance_events_total")
    frac = t.registry.gauge("attendance_profile_stage_fraction",
                            stage="dispatch")
    frac.set(0.10)
    eng.tick()  # warm
    for _ in range(4):  # sustained load builds the rate EMA
        events.inc(10_000)
        assert eng.tick() is None
    frac.set(0.80)  # stage shift far past the 20pp ceiling
    for _ in range(4):  # idle tail: rate 0 trips the drop detector
        assert eng.tick() is None
    assert eng.total_opened == 0

    # ...but the same signals DO corroborate an open incident: they
    # merge in and raise persist_sink_down via its optional set.
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    iid = eng.tick()
    assert iid is not None
    assert "circuit_open" in eng._open.conditions


def test_flap_does_not_churn_bundles(tmp_path):
    """A flapping signal keeps ONE incident open instead of opening a
    new bundle per oscillation."""
    t, eng = _engine(tmp_path)
    circuit = t.registry.gauge("attendance_circuit_state", sink="disk")
    eng.tick()
    for i in range(8):
        circuit.set(1.0 if i % 2 == 0 else 0.0)
        eng.tick()
    assert eng.total_opened == 1
    assert len(_bundle_dirs(eng)) == 1


# -- evidence bundle ---------------------------------------------------------

def test_bundle_completeness_and_checksums(tmp_path):
    t, eng = _engine(tmp_path, flight_recorder=16,
                     trace_out=str(tmp_path / "trace.json"))
    t.record_batch(ts=1.0, batch=1, events=32)
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    eng.tick()
    iid = eng.tick()
    assert iid is not None

    [bundle] = _bundle_dirs(eng)
    manifest = json.loads((bundle / "incident.json").read_text())["evidence"]
    for name in EVIDENCE_PARTS + ("diagnosis.json",):
        part = bundle / name
        assert part.is_file(), f"missing evidence part {name}"
        digest = hashlib.sha256(part.read_bytes()).hexdigest()
        assert manifest[name] == digest, f"manifest mismatch for {name}"

    flight = json.loads((bundle / "flight.json").read_text())
    assert flight["collected"] is True
    assert any(r.get("batch") == 1 for r in flight["records"])
    trace = json.loads((bundle / "trace_slice.json").read_text())
    assert trace["collected"] is True
    attribution = json.loads((bundle / "attribution.json").read_text())
    assert "collected" in attribution
    fleet = json.loads((bundle / "fleet_status.json").read_text())
    assert "instances" in fleet
    assert "attendance_incidents_open 1" in \
        (bundle / "metrics.prom").read_text()

    text, ok = incident_report(eng.dir)
    assert ok, text
    assert "sha256 ok" in text and "PASS" in text

    # Corrupt one part: the offline replay must fail the bundle.
    (bundle / "attribution.json").write_text("{}")
    text, ok = incident_report(eng.dir)
    assert not ok
    assert "digest mismatch" in text


def test_absent_subsystems_yield_stubs_not_holes(tmp_path):
    """Without flight ring / tracer / collector the bundle still has
    all five parts, each an explicit collected=false stub."""
    t, eng = _engine(tmp_path)
    t.registry.gauge("attendance_read_staleness_seconds").set(60.0)
    eng.tick()
    assert eng.tick() is not None
    [bundle] = _bundle_dirs(eng)
    for name in EVIDENCE_PARTS:
        assert (bundle / name).is_file()
    assert json.loads((bundle / "flight.json").read_text())["collected"] \
        is False
    assert json.loads(
        (bundle / "fleet_status.json").read_text())["collected"] is False
    _, ok = incident_report(bundle)
    assert ok


def test_merge_rediagnoses_on_new_conditions(tmp_path):
    """New conditions arriving while open merge into the SAME incident
    and re-rank the diagnosis (circuit alone -> + spill growth)."""
    t, eng = _engine(tmp_path)
    spilled = t.registry.counter("attendance_persist_spilled_batches_total")
    circuit = t.registry.gauge("attendance_circuit_state", sink="disk")
    eng.tick()  # warm (spilled counter seen at 0)
    circuit.set(1.0)
    iid = eng.tick()
    assert iid is not None
    assert eng._open.top_rule == "sink_circuit_open"

    spilled.inc(5)
    assert eng.tick() == iid  # merged, not a second incident
    assert eng.total_opened == 1
    assert eng._open.conditions == {"circuit_open", "spill_growth"}
    assert eng._open.top_rule == "persist_sink_down"
    [bundle] = _bundle_dirs(eng)
    dx = json.loads((bundle / "diagnosis.json").read_text())
    assert dx["top"] == "persist_sink_down"
    rec = json.loads((bundle / "incident.json").read_text())
    assert rec["diagnosis_top"] == "persist_sink_down"


# -- the three chaos scenarios (acceptance) ----------------------------------

def test_chaos_persist_sink_failure(tmp_path):
    """Persist-sink failure: breaker open + spill growth must open
    within one tick with persist_sink_down ranked first."""
    t, eng = _engine(tmp_path)
    spilled = t.registry.counter("attendance_persist_spilled_batches_total")
    circuit = t.registry.gauge("attendance_circuit_state", sink="disk")
    eng.tick()  # warm

    circuit.set(1.0)
    spilled.inc(7)
    iid = eng.tick()  # <= one evaluation tick after the breach
    assert iid is not None
    assert eng._open.conditions >= {"circuit_open", "spill_growth"}
    assert eng._open.top_rule == "persist_sink_down"
    [bundle] = _bundle_dirs(eng)
    for name in EVIDENCE_PARTS:
        assert (bundle / name).is_file()
    ranked = json.loads((bundle / "diagnosis.json").read_text())["ranked"]
    assert ranked[0]["rule"] == "persist_sink_down"


def test_chaos_recompile_storm(tmp_path):
    """Injected recompile storm via shape churn: steady-state
    fingerprints appearing after warm-up diagnose as shape_churn."""
    t, eng = _engine(tmp_path)
    t.recompiles.mark_warm()
    eng.tick()  # warm (steady counter seen)

    for i in range(4):  # shape churn: new fingerprint per batch
        t.recompiles.observe("dispatch_frame", (128 + i, 8))
    iid = eng.tick()
    assert iid is not None
    assert "steady_recompiles" in eng._open.conditions
    assert eng._open.top_rule == "shape_churn"
    [bundle] = _bundle_dirs(eng)
    for name in EVIDENCE_PARTS:
        assert (bundle / name).is_file()
    ranked = json.loads((bundle / "diagnosis.json").read_text())["ranked"]
    assert ranked[0]["rule"] == "shape_churn"
    # The recompile ledger rides in the attribution evidence.
    attribution = json.loads((bundle / "attribution.json").read_text())
    assert attribution.get("recompiles", {}).get("steady", 0) >= 4


def test_chaos_dead_federation_worker(tmp_path):
    """SIGKILLed federation worker: peer marked down while merge lag
    grows diagnoses dead_worker ahead of the broad backlog rule."""
    t, eng = _engine(tmp_path)
    peer = t.registry.gauge("attendance_fed_peer_up", peer="room-b")
    peer.set(1.0)
    lag = t.registry.histogram("attendance_fed_merge_lag_seconds")
    lag.observe(0.01)
    eng.tick()  # warm (histogram snapshot recorded)

    peer.set(0.0)  # worker killed
    for _ in range(10):
        lag.observe(30.0)  # merges now lag far over the 5s ceiling
    iid = eng.tick()
    assert iid is not None
    assert eng._open.conditions >= {"peer_down", "merge_lag"}
    assert eng._open.top_rule == "dead_worker"
    [bundle] = _bundle_dirs(eng)
    for name in EVIDENCE_PARTS:
        assert (bundle / name).is_file()
    ranked = json.loads((bundle / "diagnosis.json").read_text())["ranked"]
    names = [r["rule"] for r in ranked]
    assert names[0] == "dead_worker"
    assert "fed_merge_backlog" in names  # matched, but outranked


# -- doctor --incident replay ------------------------------------------------

def _open_clean_bundle(tmp_path):
    t, eng = _engine(tmp_path)
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    eng.tick()
    assert eng.tick() is not None
    [bundle] = _bundle_dirs(eng)
    obs.disable()
    return bundle


def test_doctor_incident_exit_zero_on_clean_bundle(tmp_path):
    from attendance_tpu.cli import main
    bundle = _open_clean_bundle(tmp_path)
    with pytest.raises(SystemExit) as exc:
        main(["doctor", "--incident", str(bundle.parent)])
    assert exc.value.code == 0


def test_doctor_incident_exit_one_on_undiagnosed_open(tmp_path):
    from attendance_tpu.cli import main
    bundle = _open_clean_bundle(tmp_path)
    rec = json.loads((bundle / "incident.json").read_text())
    rec["cleared_unix"] = None
    rec["diagnosis_top"] = ""  # open AND undiagnosed -> operator page
    (bundle / "incident.json").write_text(json.dumps(rec))
    with pytest.raises(SystemExit) as exc:
        main(["doctor", "--incident", str(bundle)])
    assert exc.value.code == 1


def test_doctor_incident_exit_one_on_corrupt_evidence(tmp_path):
    from attendance_tpu.cli import main
    bundle = _open_clean_bundle(tmp_path)
    (bundle / "metrics.prom").write_text("tampered\n")
    with pytest.raises(SystemExit) as exc:
        main(["doctor", "--incident", str(bundle)])
    assert exc.value.code == 1


def test_doctor_incident_exit_two_on_missing_dir(tmp_path):
    from attendance_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(["doctor", "--incident", str(tmp_path / "nope")])
    assert exc.value.code == 2


def test_scrubber_recognises_bundle_family(tmp_path):
    """The rot scrubber verifies bundle parts against the incident
    manifest instead of flagging them as unknown files."""
    from attendance_tpu.utils.integrity import scrub_report
    bundle = _open_clean_bundle(tmp_path)
    text, ok = scrub_report([str(bundle)])
    assert ok, text
    assert "incident-record" in text
    assert "incident-evidence" in text


# -- fleet incidents column --------------------------------------------------

def test_fleet_incidents_column(tmp_path):
    from attendance_tpu.cli import _fleet_table
    from attendance_tpu.obs.exposition import (
        fold_headline_samples, parse_prom)
    t, eng = _engine(tmp_path)
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    eng.tick()
    assert eng.tick() is not None

    acc = fold_headline_samples(parse_prom(t.render()))
    assert acc["incidents"] == 1

    table = _fleet_table({"instances": {
        "ingest@1": {"age_s": 1.0, "pushes": 2, "spans": 0,
                     "incidents": 1},
        "serve@2": {"age_s": 1.0, "pushes": 2, "spans": 0},
    }})
    assert "incidents" in table
    lines = [l for l in table.splitlines() if "ingest@1" in l]
    assert lines and lines[0].rstrip().endswith("1")
    serve = [l for l in table.splitlines() if "serve@2" in l]
    assert serve and serve[0].rstrip().endswith("-")


def test_incident_spans_and_metrics(tmp_path):
    """Open/clear/diagnosis are first-class spans when tracing is on,
    and the counter labels the top rule."""
    t, eng = _engine(tmp_path, trace_out=str(tmp_path / "trace.json"),
                     incident_clear_ticks=1)
    circuit = t.registry.gauge("attendance_circuit_state", sink="disk")
    eng.tick()
    circuit.set(1.0)
    assert eng.tick() is not None
    circuit.set(0.0)
    assert eng.tick() is None  # clear_ticks=1

    names = [e.get("name") for e in t.tracer.export()["traceEvents"]]
    assert "incident_open" in names
    assert "incident_diagnosis" in names
    assert "incident_clear" in names
    text = t.render()
    assert 'attendance_incidents_total{rule="sink_circuit_open"} 1' in text


# -- alert-log schema field (satellite 1) ------------------------------------

def test_alert_log_events_carry_schema(tmp_path):
    from attendance_tpu.obs.slo import SloEngine
    t = obs.enable(Config(flight_recorder=8))
    path = tmp_path / "alerts.jsonl"
    eng = SloEngine(t, (), fast_s=4.0, slow_s=20.0, path=str(path))
    fpr = t.registry.gauge("attendance_bloom_measured_fpr")
    fpr.set(0.05)
    for i in range(25):
        eng.tick(now=float(i))
    events = [json.loads(l) for l in path.read_text().splitlines()]
    assert events
    assert all(e["schema"] == ALERT_SCHEMA for e in events)


def test_doctor_warns_once_on_versionless_alert_log(tmp_path):
    """Pre-17 alert logs (no schema field) replay fine with exactly
    one vintage warning row; versioned logs get no warning."""
    from attendance_tpu.obs.slo import doctor_report
    old = tmp_path / "old_alerts.jsonl"
    old.write_text(json.dumps({
        "ts": 1.0, "slo": "throughput", "state": "firing",
        "burn_fast": 20.0, "burn_slow": 16.0}) + "\n" + json.dumps({
            "ts": 2.0, "slo": "throughput", "state": "resolved",
            "burn_fast": 0.0, "burn_slow": 0.0}) + "\n")
    text, ok = doctor_report([str(old)])
    assert ok, text
    assert text.count("versionless") == 1
    assert "pre-17 log" in text

    new = tmp_path / "new_alerts.jsonl"
    new.write_text(json.dumps({
        "schema": ALERT_SCHEMA, "ts": 1.0, "slo": "throughput",
        "state": "resolved", "burn_fast": 0.0, "burn_slow": 0.0}) + "\n")
    text, ok = doctor_report([str(new)])
    assert ok, text
    assert "versionless" not in text


# -- histogram exemplars (satellite 2) ---------------------------------------

def test_exemplar_worst_observation_wins():
    from attendance_tpu.obs.registry import Registry
    reg = Registry()
    h = reg.histogram("attendance_stage_latency_seconds", stage="decode")
    h.observe(0.010, "aaaa000000000001")
    h.observe(0.120, "aaaa000000000002")  # worst traced observation
    h.observe(0.005, "aaaa000000000003")
    h.observe(0.500)  # untraced: can never be the exemplar
    assert h.exemplar(reset=False) == (0.120, "aaaa000000000002")


def test_exemplar_rendered_and_parseable():
    from attendance_tpu.obs.exposition import (
        format_prom_table, parse_exemplars, parse_prom, render)
    from attendance_tpu.obs.registry import Registry
    reg = Registry()
    h = reg.histogram("attendance_stage_latency_seconds", stage="decode")
    h.observe(0.020, "deadbeef00000001")
    text = render(reg)
    assert ' # {trace_id="deadbeef00000001"} 0.02' in text

    # The exemplar rides the landing cumulative bucket, and the plain
    # sample value still parses for pre-exemplar consumers.
    samples = parse_prom(text)
    for name, _labels, value in samples:
        float(value)  # every sample stays numeric
    ex = parse_exemplars(text)
    key = ("attendance_stage_latency_seconds", 'stage="decode"')
    assert ex[key] == (0.02, "deadbeef00000001")

    table = format_prom_table(text)
    assert "exemplar=deadbeef00000001" in table

    # Destructive read: the next scrape window starts fresh.
    assert " # {" not in render(reg)


def test_fast_path_emits_stage_exemplars(tmp_path):
    """The run loop tags decode/dispatch stage observations with the
    trace id of the batch, visible on the scrape surface."""
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.obs.exposition import parse_exemplars
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)
    config = Config(bloom_filter_capacity=5_000, batch_size=256,
                    trace_out=str(tmp_path / "trace.json"),
                    pulsar_topic="exemplar-t").validate()
    t = obs.enable(config)
    broker = MemoryBroker()
    pipe = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    roster, frames = generate_frames(3 * 256, 256, roster_size=1_000,
                                     seed=3)
    pipe.preload(roster)
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=3 * 256, idle_timeout_s=0.5)
    ex = parse_exemplars(t.render())
    stages = {labels for name, labels in ex
              if name == "attendance_stage_latency_seconds"}
    assert any('stage="decode"' in s for s in stages)
    assert any('stage="dispatch"' in s for s in stages)
    for value, trace_id in ex.values():
        assert len(trace_id) == 16
        int(trace_id, 16)


# -- striped lanes reach the flight ring (satellite 3) -----------------------

def test_striped_lanes_record_into_flight_ring(tmp_path):
    """lanes>=1 runs must land per-lane records in the flight ring so
    a SIGUSR1 dump (same ring) carries lane forensics — previously
    only the classic loop recorded batches."""
    from attendance_tpu.pipeline.events import AttendanceEvent, encode_event
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)
    dump = tmp_path / "flight.json"
    config = Config(bloom_filter_capacity=5_000, batch_size=64,
                    ingress_lanes=2, flight_recorder=64,
                    flight_path=str(dump),
                    pulsar_topic="lanes-flight").validate()
    t = obs.enable(config)
    rng = np.random.default_rng(5)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        300, replace=False)
    ids = roster[rng.integers(0, len(roster), 256)]
    payloads = [encode_event(AttendanceEvent(
        int(ids[i]), "2026-07-14T08:30:00", "LECTURE_20260714",
        True, "entry")) for i in range(256)]
    broker = MemoryBroker()
    pipe = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    pipe.preload(roster)
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    producer.send_many(payloads)
    pipe.run(max_events=None, idle_timeout_s=0.5)

    lane_recs = [r for r in t.flight.snapshot()
                 if isinstance(r, dict) and "lane" in r]
    assert lane_recs, "striped lanes never reached the flight ring"
    assert {r["lane"] for r in lane_recs} <= {0, 1}
    assert all(r.get("events", 0) >= 1 for r in lane_recs)
    t.dump_flight("test")
    doc = json.loads(dump.read_text())
    assert any("lane" in r for r in doc["records"])


# -- config / lifecycle wiring -----------------------------------------------

def test_incident_dir_alone_enables_telemetry(tmp_path):
    config = Config(incident_dir=str(tmp_path / "inc"))
    assert obs.enabled_in(config)
    t = obs.enable(config)
    assert t.incidents is not None
    assert t.incidents.clear_ticks == 3


def test_finalize_persists_open_incident(tmp_path):
    """Telemetry stop persists a still-open incident with the reason
    recorded, so a crash-adjacent shutdown never loses the record."""
    t, eng = _engine(tmp_path)
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    eng.tick()
    assert eng.tick() is not None
    obs.disable()  # runs Telemetry.stop -> incidents.finalize
    [bundle] = find_bundles(tmp_path / "incidents")
    rec = json.loads((bundle / "incident.json").read_text())
    assert rec["detail"]["finalized"] == "telemetry-stop"
    assert rec["cleared_unix"] is None
