"""Storage-rot integrity plane: checksummed durability, classified
restore, scrub, peer-assisted chain repair, and the disk/partition
chaos fault sites.

Every injected corruption class — flipped payload byte, truncated npz,
torn manifest JSON, stale digest after a partial rewrite, corrupt spill
record mid-drain — is exercised against restore AND scrub for both the
fused chain and the generic sketch-store chain; the property test
proves scrub detects 100% of deterministic ``disk_corrupt`` injections
on the CI seeds (101/202/303); the wire half covers the checksummed
framing variant (gossip + fleet pushes, legacy tolerance, loud
rejection); and the repair ladder runs end to end: quarantine ->
truncate -> aggregator re-assert -> state equality with the
pre-corruption chain.
"""

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu import chaos, obs
from attendance_tpu.config import Config
from attendance_tpu.pipeline.fast_path import (
    CHAIN_MANIFEST, SKETCH_SNAPSHOT, FusedPipeline, read_chain_state)
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient
from attendance_tpu.utils import integrity
from attendance_tpu.utils.integrity import (
    ChainIntegrityError, IntegrityError, bytes_digest, file_digest,
    scrub_paths, unwrap_record, wrap_record)

NUM_EVENTS, BATCH = 16_384, 2_048


@pytest.fixture(autouse=True)
def _reset_globals():
    chaos.disable()
    obs.disable()
    yield
    chaos.disable()
    obs.disable()


def _mkframes(seed=61):
    return generate_frames(NUM_EVENTS, BATCH, roster_size=6_000,
                           num_lectures=6, invalid_fraction=0.15,
                           seed=seed)


def _mkcfg(snap_dir="", every=2, **kw):
    return Config(bloom_filter_capacity=20_000,
                  transport_backend="memory",
                  snapshot_dir=snap_dir,
                  snapshot_every_batches=every if snap_dir else 0, **kw)


def _run_chain(tmp_path, seed=61, extra_rounds=1, **cfg_kw):
    """Build a fused chain with a base + at least one delta; returns
    (snap_dir, config, reference state dict)."""
    roster, frames = _mkframes(seed)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap), **cfg_kw)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    pipe.snapshot()  # full base
    for _ in range(extra_rounds):
        for f in frames[:2]:
            producer.send(f)
        pipe.run(max_events=2 * BATCH, idle_timeout_s=0.5)
    expect = {day: pipe.count(day) for day in pipe.lecture_days()}
    events = pipe._events_total
    pipe.cleanup()
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert chain["deltas"], "need at least one delta in the chain"
    assert chain.get("base_digest") and chain.get("digests")
    return snap, config, {"counts": expect, "events": events,
                          "chain": chain}


def _flip_mid_byte(path):
    raw = bytearray(Path(path).read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    Path(path).write_bytes(bytes(raw))


# ---------------------------------------------------------------------------
# Digest / record primitives
# ---------------------------------------------------------------------------

def test_digest_helpers_agree(tmp_path):
    data = b"storage rot is silent until it is not" * 100
    p = tmp_path / "blob"
    p.write_bytes(data)
    assert file_digest(p) == bytes_digest(data)
    assert file_digest(p, chunk_size=7) == bytes_digest(data)


def test_record_wrap_roundtrip_and_rot():
    blob = b"spill batch payload" * 50
    assert unwrap_record(wrap_record(blob)) == (blob, True)
    # Legacy record (no header): passes through unverified.
    assert unwrap_record(blob) == (blob, False)
    wrapped = bytearray(wrap_record(blob))
    wrapped[len(wrapped) // 2] ^= 0xFF
    with pytest.raises(IntegrityError):
        unwrap_record(bytes(wrapped))


def test_checksummed_frame_variant():
    from attendance_tpu.transport.framing import (
        FrameChecksumError, dec_checksummed, enc_checksummed)

    body = b"\x01\x00merge frame bytes" * 20
    assert dec_checksummed(enc_checksummed(body)) == (body, True)
    # Legacy frame: unwrapped passthrough, verified=False.
    assert dec_checksummed(body) == (body, False)
    rotten = bytearray(enc_checksummed(body))
    rotten[-3] ^= 0xFF
    with pytest.raises(FrameChecksumError):
        dec_checksummed(bytes(rotten))


# ---------------------------------------------------------------------------
# Fused chain: every corruption class, restore + scrub
# ---------------------------------------------------------------------------

def test_flipped_delta_byte_classified_and_repaired_locally(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    victim = snap / ref["chain"]["deltas"][-1]
    _flip_mid_byte(victim)

    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "digest_mismatch"
    assert exc_info.value.path.name == victim.name

    rows, ok = scrub_paths([snap])
    assert not ok
    corrupt = [r for r in rows if r.corrupt]
    assert [Path(r.path).name for r in corrupt] == [victim.name]
    assert corrupt[0].kind == "digest_mismatch"

    # Restore repairs locally: quarantine + truncate, never a crash.
    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    try:
        assert (snap / "integrity-quarantine" / victim.name).exists()
        assert not victim.exists()
        man = json.loads((snap / CHAIN_MANIFEST).read_text())
        assert victim.name not in man["deltas"]
        # Step 3 of the ladder ran eagerly: a fresh full base
        # superseded the truncated chain and verifies end to end.
        assert man["deltas"] == []
        assert not pipe2._base_stale and pipe2._writer_base_ok
        read_chain_state(snap)  # verifies digests, must not raise
    finally:
        pipe2.cleanup()
    rows, ok = scrub_paths([snap])
    assert ok, [r.as_list() for r in rows if r.corrupt]


def test_truncated_delta_npz_detected(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    victim = snap / ref["chain"]["deltas"][-1]
    raw = victim.read_bytes()
    victim.write_bytes(raw[:len(raw) // 2])
    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "digest_mismatch"
    rows, ok = scrub_paths([snap])
    assert not ok


def test_truncated_delta_without_digests_still_classified(tmp_path):
    """Legacy chain (pre-integrity manifest): truncation cannot be
    caught by a digest, but the classified structural failure must
    surface — never an opaque numpy error."""
    snap, config, ref = _run_chain(tmp_path)
    man = json.loads((snap / CHAIN_MANIFEST).read_text())
    man.pop("digests", None)
    man.pop("base_digest", None)
    (snap / CHAIN_MANIFEST).write_text(json.dumps(man))
    victim = snap / ref["chain"]["deltas"][-1]
    raw = victim.read_bytes()
    victim.write_bytes(raw[:len(raw) // 2])
    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "unreadable"


def test_torn_manifest_json_detected_and_repaired(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    manifest = snap / CHAIN_MANIFEST
    raw = manifest.read_bytes()
    manifest.write_bytes(raw[:len(raw) // 2])  # torn JSON
    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "torn_manifest"
    rows, ok = scrub_paths([snap])
    assert not ok
    assert any(r.kind == "torn_manifest" for r in rows if r.corrupt)

    # Repair: manifest quarantined, base-only restore, fresh manifest.
    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    try:
        assert pipe2._events_restored > 0  # the base still restored
        assert json.loads(manifest.read_text())["deltas"] == []
    finally:
        pipe2.cleanup()
    rows, ok = scrub_paths([snap])
    assert ok


def test_stale_digest_after_partial_rewrite(tmp_path):
    """A partial in-place rewrite (rot that changes bytes but leaves a
    parseable-SIZE file) must trip the digest even when the content is
    a perfectly well-formed npz — the manifest recorded different
    bytes."""
    snap, config, ref = _run_chain(tmp_path)
    victim = snap / ref["chain"]["deltas"][-1]
    # Rewrite the delta with a VALID npz of different content: only
    # the digest can notice (np.load would succeed happily).
    with open(victim, "wb") as f:
        np.savez(f, bank_idx=np.zeros(1, np.int32),
                 regs_rows=np.zeros((1, 1 << 14), np.uint8),
                 counts=np.zeros((2, 2), np.uint32),
                 manifest=np.frombuffer(json.dumps(
                     {"bank_of": {}, "events": 0,
                      "num_banks": 8}).encode(), np.uint8))
    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "digest_mismatch"
    rows, ok = scrub_paths([snap])
    assert not ok


def test_missing_named_delta_classified(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    (snap / ref["chain"]["deltas"][-1]).unlink()
    with pytest.raises(ChainIntegrityError) as exc_info:
        read_chain_state(snap)
    assert exc_info.value.kind == "missing"
    rows, ok = scrub_paths([snap])
    assert not ok
    assert any(r.kind == "missing" for r in rows if r.corrupt)


def test_corrupt_base_without_peer_starts_empty_loudly(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    _flip_mid_byte(snap / SKETCH_SNAPSHOT)
    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    try:
        # No peer to re-assert from: starts empty (restore returned
        # False), with the corrupt base preserved for triage.
        assert pipe2._events_restored == 0
        assert (snap / "integrity-quarantine"
                / SKETCH_SNAPSHOT).exists()
    finally:
        pipe2.cleanup()


def test_stale_base_digest_crash_window_tolerated(tmp_path):
    """The one LEGIT digest mismatch: a crash between the base's
    in-place replace and the chain-manifest reset leaves CHAIN.json
    recording the old base's digest. A structurally clean base must
    restore (chain_seq fences the stale deltas) — treating this as rot
    would turn the documented crash window into data loss."""
    snap, config, ref = _run_chain(tmp_path)
    man = json.loads((snap / CHAIN_MANIFEST).read_text())
    man["base_digest"] = "0" * 64  # stale: describes the "old" base
    (snap / CHAIN_MANIFEST).write_text(json.dumps(man))
    state = read_chain_state(snap)  # must NOT raise
    assert state["events"] == ref["events"]
    rows, ok = scrub_paths([snap])
    assert ok
    assert any(r.status == "stale-digest" for r in rows)


def test_rotted_event_segment_quarantined_not_crash(tmp_path):
    """Event-store segment files carry no digests, but their rot must
    still be classified: scrub detects it structurally (zip CRCs) and
    restore quarantines the offender and loads the survivors — never
    an opaque numpy crash, never silent."""
    snap, config, ref = _run_chain(tmp_path)
    segs = sorted((snap / "fused_events_segs").glob("segment-*.npz"))
    assert segs, "delta-mode run should write event segments"
    _flip_mid_byte(segs[0])
    rows, ok = scrub_paths([snap])
    assert not ok
    assert any(r.artifact == "events-file" for r in rows if r.corrupt)
    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    try:
        # Sketch state restored untouched; the rotted segment went to
        # quarantine and the surviving rows loaded.
        assert pipe2._events_restored == ref["events"]
        assert {d: pipe2.count(d) for d in pipe2.lecture_days()} \
            == ref["counts"]
        qdir = snap / "fused_events_segs" / "integrity-quarantine"
        assert any(qdir.glob("segment-*.npz"))
    finally:
        pipe2.cleanup()
    rows, ok = scrub_paths([snap])
    assert ok


def test_rot_in_stale_delta_never_triggers_repair(tmp_path):
    """The crash window leaves CHAIN.json naming deltas OLDER than the
    replaced base (chain_seq fences them out of restore). Rot in one
    of those never-applied files must not trigger a repair — the
    staleness skip runs before verification, so the good state
    restores untouched."""
    roster, frames = _mkframes(seed=71)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    man = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert man["deltas"]
    stale = man["deltas"][0]
    expect_events = pipe._events_total

    def crash(*a, **kw):
        raise OSError("simulated crash before chain-manifest reset")

    pipe._write_chain_manifest = crash
    with pytest.raises(OSError):
        pipe.snapshot()  # base replaced, manifest reset "crashed"
    pipe.cleanup()
    _flip_mid_byte(snap / stale)  # rot in the fenced-out stale delta
    state = read_chain_state(snap)  # must not raise, must not repair
    assert state["events"] == expect_events
    assert state["applied"] == []  # stale deltas skipped, not applied


def test_scrub_flags_orphan_deltas_as_tolerated(tmp_path):
    snap, config, ref = _run_chain(tmp_path)
    with open(snap / "delta-9999.npz", "wb") as f:
        np.savez(f, junk=np.zeros(4))
    rows, ok = scrub_paths([snap])
    assert ok  # orphans are ignored by restore, tolerated by scrub
    assert any(r.status == "orphan" for r in rows)


# ---------------------------------------------------------------------------
# Generic sketch-store chain
# ---------------------------------------------------------------------------

def _store_chain(tmp_path):
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.utils.snapshot import snapshot_sketch_store_chain

    d = tmp_path / "store-chain"
    store = MemorySketchStore(Config())
    store.bf_reserve("bf:students", 0.01, 1000)
    store.bf_add_many("bf:students", np.arange(100, dtype=np.uint32))
    store.pfadd_many("hll:unique:1", np.arange(50, dtype=np.uint32))
    snapshot_sketch_store_chain(store, d)  # base
    store.bf_add_many("bf:students",
                      np.arange(100, 200, dtype=np.uint32))
    store.pfadd_many("hll:unique:1",
                     np.arange(50, 80, dtype=np.uint32))
    snapshot_sketch_store_chain(store, d)  # delta
    return d, store


@pytest.mark.parametrize("corruption", ["flip", "truncate", "torn_manifest",
                                        "missing", "stale_rewrite"])
def test_store_chain_corruption_classes(tmp_path, corruption):
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.utils.snapshot import restore_sketch_store

    d, store = _store_chain(tmp_path)
    man = json.loads((d / "MANIFEST.json").read_text())
    victim = d / man["deltas"][0]
    if corruption == "flip":
        _flip_mid_byte(victim)
        want_kind = "digest_mismatch"
    elif corruption == "truncate":
        raw = victim.read_bytes()
        victim.write_bytes(raw[:len(raw) // 2])
        want_kind = "digest_mismatch"
    elif corruption == "torn_manifest":
        raw = (d / "MANIFEST.json").read_bytes()
        (d / "MANIFEST.json").write_bytes(raw[:len(raw) // 2])
        want_kind = "torn_manifest"
    elif corruption == "missing":
        victim.unlink()
        want_kind = "missing"
    else:  # stale_rewrite: valid npz, different bytes
        with open(victim, "wb") as f:
            np.savez(f, __manifest__=np.frombuffer(json.dumps(
                {"blooms": {}, "hll": {"kind": "rows", "keys": [],
                                       "precision": 14}}).encode(),
                np.uint8))
        want_kind = "digest_mismatch"
    restored = MemorySketchStore(Config())
    with pytest.raises(ChainIntegrityError) as exc_info:
        restore_sketch_store(restored, d)
    assert exc_info.value.kind == want_kind
    rows, ok = scrub_paths([d])
    assert not ok
    assert any(r.kind == want_kind for r in rows if r.corrupt)


def test_store_chain_clean_roundtrip_still_works(tmp_path):
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.utils.snapshot import restore_sketch_store

    d, store = _store_chain(tmp_path)
    restored = MemorySketchStore(Config())
    restore_sketch_store(restored, d)
    assert restored.pfcount("hll:unique:1") == \
        store.pfcount("hll:unique:1")
    assert bool(restored.bf_exists_many("bf:students",
                                        np.asarray([150]))[0])
    rows, ok = scrub_paths([d])
    assert ok


# ---------------------------------------------------------------------------
# Spill buffer: per-record checksums, corrupt record mid-drain
# ---------------------------------------------------------------------------

class _FlakySink:
    def __init__(self):
        self.fail = False
        self.rows = []

    def insert_batch(self, rows):
        if self.fail:
            raise RuntimeError("sink down")
        self.rows.extend(rows)

    def insert_columns(self, cols):
        self.insert_batch([tuple(v) for v in zip(*cols.values())])

    def close(self):
        pass


def test_corrupt_spill_record_dropped_mid_drain(tmp_path):
    from attendance_tpu.storage.resilient import (
        CircuitBreaker, ResilientEventStore)

    obs.enable(Config(metrics_port=-1))
    sink = _FlakySink()
    store = ResilientEventStore(
        sink, tmp_path / "spill", sink="events",
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=0.01))
    sink.fail = True
    for i in range(3):
        store.insert_batch([(i, "a")])
    files = sorted((tmp_path / "spill").glob("spill-*.pkl"))
    assert len(files) == 3
    # Every spill record carries the checksum header.
    for f in files:
        _, verified = unwrap_record(f.read_bytes())
        assert verified
    # Rot the MIDDLE record, then heal the sink and drain.
    _flip_mid_byte(files[1])
    rows, ok = scrub_paths([tmp_path / "spill"])
    assert not ok and sum(r.corrupt for r in rows) == 1
    sink.fail = False
    time.sleep(0.02)
    assert store.flush_spill(budget_s=5.0)
    # Records 0 and 2 drained in order; the rotten one was dropped
    # loudly (its frames would redeliver), never unpickled into rows.
    assert sink.rows == [(0, "a"), (2, "a")]
    reg = obs.get().registry
    total = 0.0
    for name, _kind, _help, members in reg.collect():
        if name == "attendance_spill_corrupt_records_total":
            total += sum(m.value for m in members)
    assert total == 1.0
    store.close()


def test_legacy_spill_record_still_drains(tmp_path):
    """Pre-integrity spill files (bare pickle, no header) must keep
    draining — restart adoption across the upgrade boundary."""
    import pickle

    from attendance_tpu.storage.resilient import ResilientEventStore

    spill = tmp_path / "spill"
    spill.mkdir()
    (spill / "spill-000001.pkl").write_bytes(pickle.dumps(
        {"kind": "rows", "data": [(7, "legacy")]}))
    sink = _FlakySink()
    store = ResilientEventStore(sink, spill, sink="events")
    assert store.flush_spill(budget_s=5.0)
    assert sink.rows == [(7, "legacy")]
    store.close()


# ---------------------------------------------------------------------------
# Property test: scrub detects 100% of deterministic disk_corrupt
# injections (the CI seeds)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [101, 202, 303])
def test_scrub_detects_all_disk_corrupt_injections(tmp_path, seed):
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.utils.snapshot import snapshot_sketch_store_chain

    inj = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("disk_corrupt=0.5,torn_write=0.25"),
        seed=seed)
    chaos.INJECTOR = inj
    d = tmp_path / f"chain-{seed}"
    store = MemorySketchStore(Config())
    store.bf_reserve("bf", 0.01, 500)
    rng = np.random.default_rng(seed)
    for i in range(8):
        store.bf_add_many("bf", rng.integers(0, 10_000, 32,
                                             dtype=np.uint32))
        store.pfadd_many(f"hll:{i % 3}",
                         rng.integers(0, 10_000, 32, dtype=np.uint32))
        snapshot_sketch_store_chain(store, d)
    chaos.disable()
    assert inj.disk_faults, "seeded spec never fired — grow the run"
    # Every injected disk fault whose rot STILL sits on disk (not
    # healed by a later manifest rewrite, not GC'd by compaction)
    # must be detected by scrub — 100%, no exceptions.
    surviving = integrity.surviving_disk_faults(inj.disk_faults)
    assert surviving, f"seed {seed}: every fault healed — grow the run"
    rows, ok = scrub_paths([d])
    # Detected as CORRUPT, or classified ORPHAN (a rotted file whose
    # manifest write then failed was never published — restore never
    # trusts it, so orphan-rot is accounted for, not missed).
    flagged = {r.path for r in rows
               if r.corrupt or r.status == "orphan"}
    missed = surviving - flagged
    assert not missed, f"scrub missed injected corruption: {missed}"
    corrupt = {r.path for r in rows if r.corrupt}
    if surviving & corrupt:
        assert not ok


# ---------------------------------------------------------------------------
# ENOSPC: distinct handling at the snapshot writer
# ---------------------------------------------------------------------------

def test_enospc_skips_backoff_ladder_and_counts(tmp_path):
    t = obs.enable(Config(metrics_port=-1))
    chaos.INJECTOR = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("enospc=1.0"), seed=1)
    roster, frames = _mkframes()
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap), chaos="enospc=1.0")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    try:
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for f in frames[:2]:
            producer.send(f)
        pipe.run(max_events=2 * BATCH, idle_timeout_s=0.5)
        pipe._checkpoint_async(force=True)
        pipe._flush_snapshots()
        # One ENOSPC failure jumps STRAIGHT to the capped cadence —
        # no 50ms->5s ladder of full-base attempts into a full disk.
        assert pipe._snap_fail_streak >= 8
        assert pipe._writer_backoff_s() == 5.0
        total = 0.0
        for name, _k, _h, members in t.registry.collect():
            if name == "attendance_snapshot_disk_full_total":
                total += sum(m.value for m in members)
        assert total >= 1.0
    finally:
        chaos.disable()  # writer must not fail CLEANUP's final writes
        pipe.cleanup()


# ---------------------------------------------------------------------------
# Partition blackhole windows
# ---------------------------------------------------------------------------

def test_partition_blackhole_window_deterministic():
    inj = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("partition=100ms:1.0"), seed=7)
    assert inj.blackhole("fed.gossip")          # window opens
    assert inj.blackhole("fed.gossip")          # still inside
    assert inj.injected_total("partition") == 1  # one window, one count
    time.sleep(0.12)
    assert inj.blackhole("fed.gossip")          # p=1.0: reopens
    assert inj.injected_total("partition") == 2
    quiet = chaos.ChaosInjector(chaos.ChaosSpec.parse("drop=0.5"), 7)
    assert not quiet.blackhole("fed.gossip")    # partition not armed


def test_partition_blackholes_gossip_but_converges_on_full_frame():
    from attendance_tpu.federation.gossip import Aggregator, FenceGossip

    broker = MemoryBroker()
    agg = Aggregator(client=MemoryClient(broker), topic="g",
                     num_shards=1, dead_after_s=60, precision=14)
    cfg = Config(fed_worker="w0", fed_shard=0, fed_shards=1,
                 fed_gossip_topic="g", fed_heartbeat_s=0)
    fg = FenceGossip(cfg, client=MemoryClient(broker), m_bits=512, k=3)
    chaos.INJECTOR = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("partition=10s:1.0"), seed=3)
    regs = np.ones((1, 1 << 14), np.uint8)
    counts = np.zeros((2, 2), np.uint32)
    # Blackholed: publisher believes success, nothing arrives.
    assert fg.publish_delta(np.asarray([0], np.int32), regs, counts,
                            {5: 0}, 10, 1)
    assert agg.poll(timeout_ms=200) == 0
    # Heal, then the final full frame re-asserts everything.
    chaos.disable()
    bloom = np.arange(16, dtype=np.uint32)
    assert fg.publish_full(bloom, regs, counts, {5: 0}, 10)
    assert agg.poll(timeout_ms=500) == 1
    assert 5 in agg.view.bank_of
    agg.stop()
    fg.close()


def test_partition_consume_side_is_silence_not_loss():
    from attendance_tpu.transport.memory_broker import ReceiveTimeout

    broker = MemoryBroker()
    client = MemoryClient(broker)
    inj = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("partition=150ms:1.0"), seed=5)
    wrapped = chaos.ChaosClient(client, inj)
    producer = wrapped.create_producer("t")
    consumer = wrapped.subscribe("t", "s")
    producer.send(b"payload")
    with pytest.raises(ReceiveTimeout):
        consumer.receive(timeout_millis=50)  # inside the window
    # Heal the partition (p=1.0 would reopen a fresh window on every
    # roll): the blackholed message was never lost, only unseen.
    consumer._inj = chaos.ChaosInjector(chaos.ChaosSpec.parse("off"), 5)
    msg = consumer.receive(timeout_millis=1000)
    assert bytes(msg.data()) == b"payload"  # broker retained it


# ---------------------------------------------------------------------------
# Serve-plane chain reader survives corruption
# ---------------------------------------------------------------------------

def test_chain_reader_keeps_serving_on_rot(tmp_path):
    from attendance_tpu.serve.chain import ChainEpochSource

    t = obs.enable(Config(metrics_port=-1))
    snap, config, ref = _run_chain(tmp_path)
    src = ChainEpochSource(str(snap), refresh_s=0.05)
    good = src.pin()
    assert good is not None and good.events == ref["events"]

    # Rot a delta AND touch the manifest so the fingerprint changes.
    victim = snap / ref["chain"]["deltas"][-1]
    _flip_mid_byte(victim)
    man_raw = (snap / CHAIN_MANIFEST).read_text()
    (snap / CHAIN_MANIFEST).write_text(man_raw + " ")
    assert src.reload(force=True) is False  # no new epoch, no raise
    still = src.pin()
    assert still is good  # the last good epoch keeps serving
    assert (snap / "integrity-quarantine" / victim.name).exists()
    total = 0.0
    for name, _k, _h, members in t.registry.collect():
        if name == "attendance_chain_corrupt_files_total":
            total += sum(m.value for m in members)
    assert total >= 1.0
    src.stop()


# ---------------------------------------------------------------------------
# Peer-assisted repair ladder, end to end
# ---------------------------------------------------------------------------

def test_peer_reassert_repairs_corrupt_delta_end_to_end(tmp_path):
    """The full ladder: a federated worker's chain rots, a fresh
    pipeline quarantines the delta, asks the aggregator (whose
    retained per-worker view folded that delta's banks when it was
    gossiped) to re-assert, and restores state EQUAL to the
    pre-corruption chain."""
    from attendance_tpu.federation.gossip import Aggregator

    broker = MemoryBroker()
    agg = Aggregator(client=MemoryClient(broker),
                     topic="attendance-fed-gossip", num_shards=1,
                     dead_after_s=600, precision=14)

    roster, frames = _mkframes(seed=91)
    frames = list(frames)
    snap = tmp_path / "snaps"
    fed_kw = dict(fed_worker="w0", fed_shard=0, fed_shards=1,
                  fed_heartbeat_s=0)
    config = _mkcfg(str(snap), **fed_kw)
    client = MemoryClient(broker)
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    pipe.snapshot()
    for f in frames[:2]:
        producer.send(f)
    pipe.run(max_events=2 * BATCH, idle_timeout_s=0.5)
    expect = {day: pipe.count(day) for day in pipe.lecture_days()}
    expect_events = pipe._events_total
    expect_bloom = np.asarray(pipe.state.bloom_bits).copy()
    pipe.cleanup()

    # The aggregator folds everything the worker gossiped (fences +
    # the cleanup flush), retaining the worker's own contribution.
    while agg.poll(timeout_ms=300) > 0:
        pass
    assert "w0" in agg.view.worker_state

    # Rot the newest delta, then restore a fresh federated pipeline.
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert chain["deltas"]
    _flip_mid_byte(snap / chain["deltas"][-1])

    # Serve repair requests from a background thread (the worker's
    # restore blocks on the re-assert round-trip).
    stop = threading.Event()

    def _serve():
        while not stop.is_set():
            agg.poll(timeout_ms=100)

    server = threading.Thread(target=_serve, daemon=True)
    server.start()
    try:
        pipe2 = FusedPipeline(_mkcfg(str(snap), **fed_kw),
                              client=MemoryClient(broker), num_banks=8)
    finally:
        stop.set()
        server.join(timeout=2)
    try:
        got = {day: pipe2.count(day) for day in pipe2.lecture_days()}
        assert got == expect, "re-assert did not recover the lost banks"
        assert pipe2._events_total == expect_events
        assert (np.asarray(pipe2.state.bloom_bits)
                == expect_bloom).all()
        assert (snap / "integrity-quarantine"
                / chain["deltas"][-1]).exists()
    finally:
        pipe2.cleanup()


# ---------------------------------------------------------------------------
# Scrub CLI verb + doctor --scrub
# ---------------------------------------------------------------------------

def test_scrub_cli_verb_and_doctor_scrub(tmp_path, capsys):
    from attendance_tpu import cli

    snap, config, ref = _run_chain(tmp_path)
    cli.main(["scrub", str(snap)])  # clean chain: exit 0 (no raise)
    out = capsys.readouterr().out
    assert "PASS" in out

    _flip_mid_byte(snap / ref["chain"]["deltas"][-1])
    with pytest.raises(SystemExit) as exc_info:
        cli.main(["scrub", str(snap)])
    assert exc_info.value.code == 1
    out = capsys.readouterr().out
    assert "digest_mismatch" in out

    with pytest.raises(SystemExit) as exc_info:
        cli.main(["doctor", "--scrub", str(snap)])
    assert exc_info.value.code == 1

    with pytest.raises(SystemExit) as exc_info:
        cli.main(["scrub", str(tmp_path / "no-such-dir")])
    assert exc_info.value.code == 2


def test_quarantine_sidecar_uses_shared_digest(tmp_path):
    from attendance_tpu.transport.quarantine import Quarantine, list_entries

    q = Quarantine(tmp_path / "q")
    q.put(b"poison frame", topic="t", reason="decode")
    (entry,) = list_entries(tmp_path / "q")
    assert entry["sha256"] == bytes_digest(b"poison frame")
    rows, ok = scrub_paths([tmp_path / "q"])
    assert ok
    # Rot the frame: the sidecar digest catches it.
    _flip_mid_byte(Path(entry["frame"]))
    rows, ok = scrub_paths([tmp_path / "q"])
    assert not ok
