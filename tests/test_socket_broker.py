"""Cross-process transport (transport.socket_broker): protocol
semantics, crash takeover over a dropped connection, and the VERDICT r03
2-process competing-consumer bridge scale-out — the reference's Pulsar
Shared-subscription model (reference attendance_processor.py:30-34)
demonstrated across real OS processes on the framework's own broker.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.transport import ReceiveTimeout, make_client
from attendance_tpu.transport.socket_broker import (
    BrokerServer, SocketClient)


def test_socket_produce_consume_ack_nack(server):
    client = SocketClient(server.address)
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    for i in range(5):
        producer.send(f"m{i}".encode())
    msgs = consumer.receive_many(10, timeout_millis=2000)
    assert [m.data() for m in msgs] == [f"m{i}".encode() for i in range(5)]
    assert consumer.backlog() == 5  # delivered, unacked
    consumer.acknowledge_many(msgs[:4])
    assert consumer.backlog() == 1
    # Nack -> redelivery with a bumped count.
    consumer.negative_acknowledge(msgs[4])
    redelivered = consumer.receive(timeout_millis=2000)
    assert redelivered.data() == b"m4"
    assert redelivered.redelivery_count == 1
    consumer.acknowledge(redelivered)
    assert consumer.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        consumer.receive_many_raw(1, timeout_millis=50)
    client.close()


def test_socket_raw_lane_and_ack_ids(server):
    client = SocketClient(server.address)
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    payloads = [f"p{i}".encode() for i in range(8)]
    for p in payloads:
        producer.send(p)
    raw = consumer.receive_many_raw(8, timeout_millis=2000)
    assert [t[1] for t in raw] == payloads
    consumer.acknowledge_ids([t[0] for t in raw])
    assert consumer.backlog() == 0
    client.close()


def test_make_client_socket_backend(server):
    config = Config(transport_backend="socket",
                    socket_broker=server.address)
    client = make_client(config)
    client.create_producer("x").send(b"hello")
    assert client.subscribe("x", "s").receive(
        timeout_millis=2000).data() == b"hello"
    client.close()


def test_blocked_consumer_does_not_stall_producer(server):
    """A consumer parked in a blocking receive holds only ITS dedicated
    connection; a producer on the same client must complete immediately
    (ADVICE r04: the shared-channel design serialized threaded clients
    behind the consumer's up-to-10s server wait round)."""
    import threading

    client = SocketClient(server.address)
    consumer = client.subscribe("t", "sub")
    producer = client.create_producer("t")
    got = []
    th = threading.Thread(
        target=lambda: got.extend(
            consumer.receive_many(1, timeout_millis=8000)))
    th.start()
    time.sleep(0.3)  # let the consumer enter its blocking server wait
    t0 = time.monotonic()
    producer.send(b"hello")
    assert time.monotonic() - t0 < 1.0, \
        "producer stalled behind the blocked consumer's channel"
    th.join(timeout=8)
    assert [m.data() for m in got] == [b"hello"]
    client.close()


def test_consumer_close_quiet_when_broker_dead():
    """consumer.close()/client.close() after the broker died must not
    raise (ADVICE r04): the server's connection-drop takeover already
    requeues unacked messages, and raising would mask the original
    failure in teardown paths."""
    server = BrokerServer().start()
    client = SocketClient(server.address)
    consumer = client.subscribe("t", "sub")
    client.create_producer("t").send(b"x")
    assert consumer.receive(timeout_millis=2000).data() == b"x"
    server.stop()
    # Sever the consumer's channel so the close-RPC genuinely fails
    # (stop() alone only closes the listener; live connections linger).
    consumer._rpc._sock.close()
    consumer.close()  # no raise
    client.close()  # no raise


def test_crash_takeover_across_connections(server):
    """A dropped CONNECTION (process crash) requeues its consumers'
    unacked messages for surviving competitors — the Pulsar takeover
    the reference relies on, across the process boundary."""
    victim = SocketClient(server.address)
    survivor = SocketClient(server.address)
    producer = survivor.create_producer("t")
    cv = victim.subscribe("t", "shared")
    cs = survivor.subscribe("t", "shared")
    for i in range(4):
        producer.send(f"m{i}".encode())
    taken = cv.receive_many(2, timeout_millis=2000)
    assert len(taken) == 2
    # Simulate a crash: drop the victim's TCP connections (each
    # consumer holds a dedicated one; a real process death drops all).
    cv._rpc.close()
    victim._rpc.close()
    deadline = time.monotonic() + 5
    got = []
    while len(got) < 4 and time.monotonic() < deadline:
        try:
            for m in cs.receive_many(4, timeout_millis=300):
                got.append(m.data())
                cs.acknowledge(m)
        except ReceiveTimeout:
            pass
    # The survivor ends up with ALL messages: its own two plus the
    # victim's requeued two (redelivered, any order).
    assert sorted(got) == [f"m{i}".encode() for i in range(4)]
    survivor.close()


def test_two_process_bridge_scaleout(server, tmp_path):
    """VERDICT r03 #4: two bridge PROCESSES competing on one shared
    subscription — disjoint delivery (every JSON message converted
    exactly once), aggregate accounting summing to the published count,
    and both competitors doing real work."""
    from attendance_tpu.pipeline.bridge import BINARY_TOPIC_SUFFIX
    from attendance_tpu.pipeline.events import (
        decode_planar_batch, encode_event)
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.transport.memory_broker import MemoryClient

    topic = Config().pulsar_topic
    outs = [tmp_path / f"bridge{i}.json" for i in range(2)]
    env = dict(os.environ, PYTHONPATH=str(Path(__file__).parent.parent))
    procs = [
        subprocess.Popen(
            [sys.executable,
             str(Path(__file__).parent / "bridge_worker.py"),
             server.address, str(out), "1.5"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for out in outs]
    try:
        # Publish only once BOTH competitors joined the subscription, so
        # neither can drain the topic before the other exists.
        deadline = time.monotonic() + 120
        while server.consumer_count(topic, "attendance_bridge") < 2:
            assert time.monotonic() < deadline, \
                "bridge workers failed to subscribe"
            for p in procs:
                assert p.poll() is None, p.communicate()[0][-4000:]
            time.sleep(0.1)

        report = generate_student_data(seed=41, num_students=800,
                                       num_invalid=60)
        publish = server.broker.topic(topic).publish
        for e in report.events:
            publish(encode_event(e))

        logs = [p.communicate(timeout=180)[0] for p in procs]
        for p, log in zip(procs, logs):
            assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    accounting = [json.loads(o.read_text()) for o in outs]
    # Aggregate accounting: every message converted exactly once
    # across the two processes, none dead-lettered.
    assert sum(a["events"] for a in accounting) == report.message_count
    assert all(a["dead_lettered"] == 0 for a in accounting)
    # Real competition: both processes converted a nontrivial share.
    assert all(a["events"] > 0 for a in accounting), accounting
    # The JSON subscription fully drained and acked.
    sub = server.broker.topic(topic).subscription("attendance_bridge")
    assert sub.backlog() == 0

    # Exactly one binary frame set out: drain the out topic and match
    # the decoded union against the source events one-to-one.
    client = MemoryClient(server.broker)
    consumer = client.subscribe(topic + BINARY_TOPIC_SUFFIX, "verify")
    frames = []
    while True:
        try:
            frames.extend(consumer.receive_many(64, timeout_millis=200))
        except ReceiveTimeout:
            break
    assert len(frames) == sum(a["batches"] for a in accounting)
    cols = [decode_planar_batch(m.data()) for m in frames]
    got = np.concatenate([c["micros"] for c in cols])
    want = np.sort(np.array(
        [int(np.int64(m)) for m in _expected_micros(report.events)],
        np.int64))
    assert len(got) == report.message_count
    np.testing.assert_array_equal(np.sort(got), want)


def _expected_micros(events):
    from attendance_tpu.pipeline.events import _iso_to_micros
    return [_iso_to_micros(e.timestamp) for e in events]


def test_socket_chunk_lane_and_send_many(server):
    """The chunk lane crosses the wire: whole-batch settle, nack,
    explode-to-per-message, and bulk publish in one round-trip."""
    client = SocketClient(server.address)
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    first = producer.send_many([b"m%d" % i for i in range(6)])
    assert first >= 0

    cid, toks = consumer.receive_chunk(3, timeout_millis=2000)
    assert [t[1] for t in toks] == [b"m0", b"m1", b"m2"]
    consumer.acknowledge_chunk(cid)
    assert consumer.backlog() == 3

    cid2, toks2 = consumer.receive_chunk(2, timeout_millis=2000)
    consumer.nack_chunk(cid2)
    cid3, toks3 = consumer.receive_chunk(10, timeout_millis=2000)
    got = {t[1]: t[2] for t in toks3}
    assert got[b"m5"] == 0 and got[b"m3"] == 1 and got[b"m4"] == 1

    # explode -> per-message surface applies cross-process too
    consumer.explode_chunk(cid3)
    consumer.acknowledge_ids([t[0] for t in toks3])
    assert consumer.backlog() == 0
    client.close()


def test_bridge_over_socket_uses_chunk_lane(server):
    """A bridge on the socket transport feature-detects the chunk lane
    and converts a stream end to end across the protocol."""
    from attendance_tpu.pipeline.bridge import JsonBinaryBridge
    from attendance_tpu.pipeline.events import (
        decode_planar_batch, encode_event)
    from attendance_tpu.pipeline.generator import generate_student_data

    config = Config(transport_backend="socket",
                    socket_broker=server.address, batch_size=256)
    bridge = JsonBinaryBridge(config, client=SocketClient(server.address))
    assert bridge._chunk  # the wire exposes the lane
    report = generate_student_data(seed=59, num_students=60,
                                   num_invalid=6)
    producer = SocketClient(server.address).create_producer(
        config.pulsar_topic)
    producer.send_many([encode_event(e) for e in report.events])
    bridge.run(max_events=report.message_count, idle_timeout_s=0.5)
    assert bridge.metrics.events == report.message_count
    assert bridge.consumer.backlog() == 0

    # the binary frames landed on the out topic
    verify = SocketClient(server.address).subscribe(
        bridge.out_topic, "verify")
    total = 0
    while total < report.message_count:
        cid, toks = verify.receive_chunk(64, timeout_millis=2000)
        total += sum(
            len(decode_planar_batch(t[1])["student_id"]) for t in toks)
        verify.acknowledge_chunk(cid)
    assert total == report.message_count


def test_bridge_worker_kill9_resumes_exactly(server, tmp_path):
    """Hard-crash soak across processes: a bridge worker is SIGKILLed
    mid-stream; its unacked chunks redeliver to a successor process,
    and the deduplicated union of converted events equals the source
    set exactly (at-least-once + idempotent sinks — SURVEY.md §5)."""
    import signal

    from attendance_tpu.pipeline.bridge import BINARY_TOPIC_SUFFIX
    from attendance_tpu.pipeline.events import (
        decode_planar_batch, encode_event)
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.transport.memory_broker import MemoryClient

    topic = Config().pulsar_topic
    env = dict(os.environ, PYTHONPATH=str(Path(__file__).parent.parent),
               # small batches so conversion spans many chunk
               # round-trips and the kill lands mid-stream
               ATP_BRIDGE_BATCH="64")
    report = generate_student_data(seed=67, num_students=600,
                                   num_invalid=40)
    server.broker.topic(topic).publish_many(
        [encode_event(e) for e in report.events])

    def spawn(out):
        return subprocess.Popen(
            [sys.executable,
             str(Path(__file__).parent / "bridge_worker.py"),
             server.address, str(out), "1.5"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)

    victim = spawn(tmp_path / "v.json")
    try:
        # Wait for REAL mid-stream progress: some frames out, backlog
        # still nonzero — then hard-kill.
        out_topic = server.broker.topic(topic + BINARY_TOPIC_SUFFIX)
        sub = server.broker.topic(topic).subscription("attendance_bridge")
        deadline = time.monotonic() + 120
        while True:
            assert time.monotonic() < deadline, "no mid-stream window"
            frames_out = len(out_topic.retained)
            # Require several chunks of REMAINING work, not just a
            # nonzero backlog (which could be the final in-flight
            # chunk): the kill must land with work left for the
            # successor, or the run degrades to a skip below.
            if frames_out >= 3 and sub.backlog() > 3 * 64:
                break
            if victim.poll() is not None:
                pytest.skip("worker finished before the kill window "
                            "(host too fast for a mid-stream kill)")
            time.sleep(0.005)
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
    finally:
        if victim.poll() is None:
            victim.kill()
    if server.broker.topic(topic).subscription(
            "attendance_bridge").backlog() == 0:
        pytest.skip("victim drained everything in the signal-delivery "
                    "gap; no crash window this run")

    successor = spawn(tmp_path / "s.json")
    log = successor.communicate(timeout=180)[0]
    assert successor.returncode == 0, log[-4000:]
    assert json.loads((tmp_path / "s.json").read_text())["events"] > 0

    # The victim's unacked messages redelivered: nothing lost.
    assert server.broker.topic(topic).subscription(
        "attendance_bridge").backlog() == 0

    # Dedup the union of all emitted frames: exactly the source set
    # (duplicates allowed by at-least-once; absences are failures).
    consumer = MemoryClient(server.broker).subscribe(
        topic + BINARY_TOPIC_SUFFIX, "verify")
    got = set()
    total = 0
    while True:
        try:
            for m in consumer.receive_many(64, timeout_millis=200):
                c = decode_planar_batch(m.data())
                total += len(c["micros"])
                got.update(zip(c["micros"].tolist(),
                               c["student_id"].tolist()))
        except ReceiveTimeout:
            break
    want = {(m, e.student_id & 0xFFFFFFFF)
            for m, e in zip(_expected_micros(report.events),
                            report.events)}
    assert got == want, (len(got), len(want))
    # Content-identical duplicate source events dedup to one pair, so
    # the set equality alone can't see one of them going missing; the
    # aggregate count closes that gap (>=: redelivery duplicates are
    # the at-least-once contract).
    assert total >= report.message_count, (total, report.message_count)


def test_many_concurrent_clients_exact_accounting(server):
    """8 connections hammering one topic concurrently — 4 producers,
    4 competing consumers on one shared subscription: exactly-once
    accounting of every published message, no loss, no duplication,
    under real thread/connection interleaving."""
    import threading

    n_producers, per_producer, n_consumers = 4, 2_000, 4
    total = n_producers * per_producer

    def produce(pid):
        client = SocketClient(server.address)
        try:
            prod = client.create_producer("t")
            # mix of bulk and single publishes
            msgs = [b"%d:%d" % (pid, i) for i in range(per_producer)]
            prod.send_many(msgs[: per_producer // 2])
            for m in msgs[per_producer // 2:]:
                prod.send(m)
        finally:
            client.close()

    got_lock = threading.Lock()
    got = []
    done = threading.Event()  # set once every producer finished

    def consume():
        client = SocketClient(server.address)
        try:
            cons = client.subscribe("t", "sub")
            while True:
                try:
                    cid, toks = cons.receive_chunk(256,
                                                   timeout_millis=400)
                except ReceiveTimeout:
                    # Quiet window: only terminal once the producers
                    # are done AND the queue is settled — a timeout
                    # while producers are merely descheduled (1-core
                    # host) must not end the consumer early.
                    if done.is_set() and cons.backlog() == 0:
                        return
                    continue
                cons.acknowledge_chunk(cid)
                with got_lock:
                    got.extend(t[1] for t in toks)
        finally:
            client.close()

    consumers = [threading.Thread(target=consume)
                 for _ in range(n_consumers)]
    producers = [threading.Thread(target=produce, args=(pid,))
                 for pid in range(n_producers)]
    for t in consumers + producers:
        t.start()
    for t in producers:
        t.join(timeout=60)
    done.set()
    for t in consumers:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in consumers + producers)

    assert len(got) == total, (len(got), total)  # no loss, no dupes
    want = {b"%d:%d" % (p, i) for p in range(n_producers)
            for i in range(per_producer)}
    assert set(got) == want
    assert server.broker.topic("t").subscription("sub").backlog() == 0
