"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

Exercises the hash-prefix sharded Bloom/HLL and the OR/max collectives
(SURVEY.md §4 "multi-chip without a pod"): results must be identical to
the single-device reference models for every (dp, sp) mesh shape.
"""

import jax
import numpy as np
import pytest

from attendance_tpu.models.hll import (
    estimate_from_histogram, hll_bucket_rank_np)
from attendance_tpu.parallel.sharded import ShardedSketchEngine, make_mesh

# Kept deliberately small: every (mesh shape, layout) pair compiles its
# own shard_map programs, and XLA:CPU compiles of the scatter kernels run
# tens of seconds before the persistent cache warms.
# (3, 2): dp does not divide the preload chunk or power-of-two batch
# sizes — regression shape for the dp-rounded chunked preload.
MESH_SHAPES = [(1, 8), (2, 4), (3, 2)]


def engine(dp, sp, **kw):
    mesh = make_mesh(num_shards=sp, num_replicas=dp)
    return ShardedSketchEngine(mesh, capacity=kw.pop("capacity", 20_000),
                               error_rate=0.01, num_banks=8, **kw)


def test_mesh_requires_enough_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    with pytest.raises(ValueError):
        make_mesh(num_shards=16, num_replicas=1)


@pytest.mark.parametrize("dp,sp", MESH_SHAPES)
def test_no_false_negatives_any_mesh(dp, sp):
    eng = engine(dp, sp, layout="blocked")
    roster = np.arange(10_000, 15_000, dtype=np.uint32)
    eng.preload(roster)
    assert eng.contains(roster).all()


@pytest.mark.parametrize("dp,sp", [(2, 4)])
def test_sharded_matches_single_device(dp, sp):
    """Same inputs -> bit-identical validity and identical counts on every
    mesh shape (the collectives change nothing semantically)."""
    ref = engine(1, 1)
    eng = engine(dp, sp)
    roster = np.arange(10_000, 14_000, dtype=np.uint32)
    ref.preload(roster)
    eng.preload(roster)

    rng = np.random.default_rng(0)
    keys = rng.choice(
        np.concatenate([roster, np.arange(1 << 20, (1 << 20) + 4_000,
                                          dtype=np.uint32)]), size=4_096)
    banks = rng.integers(0, 8, size=4_096).astype(np.int32)
    v_ref = ref.step(keys, banks)
    v_eng = eng.step(keys, banks)
    np.testing.assert_array_equal(v_ref, v_eng)
    for b in range(8):
        assert ref.count(b) == eng.count(b)


@pytest.mark.parametrize("dp,sp", [(2, 4)])
def test_step_words_matches_step(dp, sp):
    """The packed word wire onto the mesh must be observationally
    identical to the (keys, banks, mask) wire: same validity, same
    register state, including padded lanes."""
    from attendance_tpu.models.fused import pack_words

    ref = engine(dp, sp)
    eng = engine(dp, sp)
    roster = np.arange(10_000, 14_000, dtype=np.uint32)
    ref.preload(roster)
    eng.preload(roster)

    rng = np.random.default_rng(7)
    n = 3_000  # pads to 4096
    keys = rng.choice(
        np.concatenate([roster, np.arange(1 << 20, (1 << 20) + 4_000,
                                          dtype=np.uint32)]),
        size=n).astype(np.uint32)
    banks = rng.integers(0, 8, size=n).astype(np.int32)
    v_ref = np.asarray(ref.step(keys, banks))
    kw = int(keys.max()).bit_length()
    padded = ((4096 + dp - 1) // dp) * dp
    words = pack_words(keys, banks, kw, padded)
    v_eng = np.asarray(eng.step_words(words, n, kw))
    np.testing.assert_array_equal(v_ref, v_eng)
    for b in range(8):
        assert ref.count(b) == eng.count(b)


def test_dp_replicas_converge_to_union_state():
    """After a step, every replica holds the OR/max-merged state: keys
    processed by replica 0 must be countable when queried via any replica
    (state replicated across dp is kept consistent by the collectives)."""
    eng = engine(2, 4)
    roster = np.arange(20_000, 24_000, dtype=np.uint32)
    eng.preload(roster)
    keys = roster[:4_000]
    banks = np.zeros(4_000, dtype=np.int32)
    valid = eng.step(keys, banks)
    assert valid.all()
    # exact uniques vs HLL estimate (sigma ~0.81% at p=14)
    est = eng.count(0)
    assert est == pytest.approx(4_000, rel=0.05)


def test_hll_accuracy_across_cardinalities():
    eng = engine(2, 4, capacity=300_000)
    rng = np.random.default_rng(1)
    for bank, n in enumerate([10, 1_000, 100_000]):
        keys = rng.choice(1 << 31, size=n, replace=False).astype(np.uint32)
        eng.preload(keys)
        eng.step(keys, np.full(n, bank, dtype=np.int32))
        est = eng.count(bank)
        tol = 0.05 if n >= 1_000 else 0.0
        assert est == pytest.approx(n, rel=tol, abs=2), (bank, n, est)


def test_sharded_hist_matches_numpy_oracle():
    """Device histogram + Ertl estimate == pure-numpy mirror computation."""
    rng = np.random.default_rng(2)
    keys = rng.choice(1 << 30, size=50_000, replace=False).astype(np.uint32)
    # numpy oracle: same hash -> same registers
    bucket, rank = hll_bucket_rank_np(keys, 14)
    regs = np.zeros(1 << 14, dtype=np.uint8)
    np.maximum.at(regs, bucket, rank.astype(np.uint8))
    oracle = int(round(estimate_from_histogram(
        np.bincount(regs, minlength=52), 14)))
    eng = engine(2, 4, capacity=60_000)
    eng.preload(keys)
    eng.step(keys, np.zeros(len(keys), dtype=np.int32))
    assert eng.count(0) == oracle


def test_replica_sync_modes_equivalent():
    """'step' (per-batch union) and 'query' (deferred union) replica
    sync must be observationally identical: same validity, same counts,
    same merged snapshot state."""
    import numpy as np

    from attendance_tpu.parallel.sharded import (
        ShardedSketchEngine, make_mesh)

    rng = np.random.default_rng(5)
    roster = rng.choice(1 << 20, 4000, replace=False).astype(np.uint32)
    engines = {}
    for mode in ("step", "query"):
        eng = ShardedSketchEngine(make_mesh(num_shards=2, num_replicas=4),
                                  capacity=10_000, error_rate=0.01,
                                  num_banks=4, replica_sync=mode)
        eng.preload(roster)
        engines[mode] = eng

    valids = {}
    for mode, eng in engines.items():
        outs = []
        for i in range(6):
            keys = np.where(rng.random(500) < 0.5,
                            roster[(np.arange(500) * (i + 7)) % len(roster)],
                            (1 << 21) + np.arange(500) * (i + 1)
                            ).astype(np.uint32)
            banks = (np.arange(500) % 4).astype(np.int32)
            outs.append(np.asarray(eng.step(keys, banks)))
        valids[mode] = outs
        rng = np.random.default_rng(5)
        rng.choice(1 << 20, 4000, replace=False)  # re-sync the stream rng

    for a, b in zip(valids["step"], valids["query"]):
        assert np.array_equal(a, b)
    for bank in range(4):
        assert engines["step"].count(bank) == engines["query"].count(bank)
    bits_s, regs_s = engines["step"].get_state()
    bits_q, regs_q = engines["query"].get_state()
    assert np.array_equal(bits_s, bits_q)
    assert np.array_equal(regs_s, regs_q)


def test_replica_sync_cross_mode_restore():
    """A snapshot taken in one sync mode restores into the other (state
    is merged/global in both)."""
    import numpy as np

    from attendance_tpu.parallel.sharded import (
        ShardedSketchEngine, make_mesh)

    rng = np.random.default_rng(9)
    roster = rng.choice(1 << 20, 2000, replace=False).astype(np.uint32)
    src = ShardedSketchEngine(make_mesh(num_shards=4, num_replicas=2),
                              capacity=10_000, error_rate=0.01,
                              num_banks=4, replica_sync="query")
    src.preload(roster)
    keys = roster[:1000]
    banks = (np.arange(1000) % 4).astype(np.int32)
    src.step(keys, banks)
    bits, regs = src.get_state()

    dst = ShardedSketchEngine(make_mesh(num_shards=2, num_replicas=4),
                              capacity=10_000, error_rate=0.01,
                              num_banks=4, replica_sync="step")
    dst.set_state(bits, regs)
    for bank in range(4):
        assert dst.count(bank) == src.count(bank)
    assert np.asarray(dst.contains(keys)).all()


def test_single_device_mesh_delegates_bit_identically():
    """The (1,1) mesh compiles the single-chip kernel suite behind the
    engine surface (parallel.sharded._build_single_kernels — the
    tunneled-chip fix, PARITY.md r04 forensics). Every wire, query and
    snapshot answer must be bit-identical to the shard_map build on a
    multi-device mesh."""
    from attendance_tpu.models.fused import (
        delta_scan, pack_delta, pack_seg, pack_words, pick_delta_width)

    single = engine(1, 1)
    multi = engine(2, 4)
    assert single.single and not multi.single
    roster = np.arange(30_000, 38_000, dtype=np.uint32)
    single.preload(roster)
    multi.preload(roster)

    rng = np.random.default_rng(3)
    n = 2_048
    keys = np.where(rng.random(n) < 0.7, rng.choice(roster, n),
                    rng.integers(1 << 20, 1 << 21, n)).astype(np.uint32)
    banks = rng.integers(0, 8, n).astype(np.uint32)

    # word wire
    kw = 17
    for eng in (single, multi):
        words = pack_words(keys, banks, kw, eng.padded_size(n))
        v = eng.step_words(words, n, kw)
        np.testing.assert_array_equal(
            np.asarray(v), np.isin(keys, roster) | np.asarray(v))
    # seg + delta wires (per-replica packed: dp=1 single, dp=2 multi)
    for mode in ("seg", "delta"):
        for eng in (single, multi):
            dp = eng.dp
            pl = eng.padded_size(n) // dp
            bounds = [min(n, r * pl) for r in range(dp + 1)]
            if mode == "seg":
                width = 21
                packs = [pack_seg(keys[bounds[r]:bounds[r + 1]],
                                  banks[bounds[r]:bounds[r + 1]],
                                  width, pl, 8) for r in range(dp)]
            else:
                scans = [delta_scan(keys[bounds[r]:bounds[r + 1]],
                                    banks[bounds[r]:bounds[r + 1]], 8)
                         for r in range(dp)]
                width = pick_delta_width(1, max(s[-1] for s in scans))
                packs = [pack_delta(keys[bounds[r]:bounds[r + 1]],
                                    banks[bounds[r]:bounds[r + 1]],
                                    width, pl, 8, scan=scans[r])
                         for r in range(dp)]
            bufs = np.stack([p[0] for p in packs])
            eng.step_narrow(bufs, mode, width, pl)

    # Identical answers on every query surface.
    probe = np.concatenate([roster[:1000],
                            np.arange(1 << 22, (1 << 22) + 1000,
                                      dtype=np.uint32)])
    np.testing.assert_array_equal(single.contains(probe),
                                  multi.contains(probe))
    np.testing.assert_array_equal(single.count_all(), multi.count_all())
    assert single.validity_counts() == multi.validity_counts()
    assert single.fill_fraction() == pytest.approx(
        multi.fill_fraction(), rel=1e-6)
    b1, r1 = single.get_state()
    b2, r2 = multi.get_state()
    np.testing.assert_array_equal(b1, b2)
    np.testing.assert_array_equal(r1, r2)

    # Snapshot round-trip across the two builds restores exactly.
    fresh = engine(1, 1)
    fresh.set_state(b2, r2)
    fresh.set_counts(multi.get_counts())
    np.testing.assert_array_equal(fresh.get_state()[0], b2)
    assert fresh.validity_counts() == multi.validity_counts()
