"""Fused pipeline tests: columnar store, load generator, end-to-end run."""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.analyzer import AttendanceAnalyzer
from attendance_tpu.pipeline.events import decode_binary_batch
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import (
    frame_from_columns, generate_frames, synth_columns)
from attendance_tpu.storage.columnar_store import ColumnarEventStore
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


def test_loadgen_frame_roundtrip():
    rng = np.random.default_rng(0)
    roster = np.arange(10_000, 11_000, dtype=np.uint32)
    cols = synth_columns(rng, 500, roster, num_lectures=4)
    decoded = decode_binary_batch(frame_from_columns(cols))
    for name in ("student_id", "lecture_day", "micros", "is_valid",
                 "event_type"):
        np.testing.assert_array_equal(decoded[name], cols[name])


def test_columnar_store_dedup_last_write_wins():
    store = ColumnarEventStore()
    base = {
        "student_id": np.array([1, 2], np.uint32),
        "lecture_day": np.array([20260101, 20260101], np.uint32),
        "micros": np.array([10, 20], np.int64),
        "is_valid": np.array([True, True]),
        "event_type": np.array([0, 0], np.int8),
    }
    store.insert_columns(base)
    replay = dict(base)
    replay["is_valid"] = np.array([False, True])  # last write wins
    store.insert_columns(replay)
    df = store.to_dataframe()
    assert len(df) == 2
    assert not df[df.student_id == 1].is_valid.item()
    assert df[df.student_id == 2].is_valid.item()


def test_columnar_store_save_load(tmp_path):
    store = ColumnarEventStore()
    rng = np.random.default_rng(1)
    store.insert_columns(synth_columns(
        rng, 300, np.arange(10_000, 10_100, dtype=np.uint32), 4))
    p = tmp_path / "events.npz"
    store.save(p)
    restored = ColumnarEventStore()
    restored.load(p)
    assert restored.to_dataframe().equals(store.to_dataframe())


def test_fused_pipeline_end_to_end():
    """Bulk frames -> fused dispatch -> columnar store; validity must
    match the loadgen ground truth (the reference's oracle, SURVEY.md §4)
    and the HLL counts must track exact uniques."""
    config = Config(bloom_filter_capacity=50_000,
                    transport_backend="memory")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)

    num_events, batch = 40_000, 4_096
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=20_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=3)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.5)

    assert pipe.metrics.events == num_events
    assert pipe.consumer.backlog() == 0  # everything acked post-commit

    df = pipe.store.to_dataframe(deduplicate=False)
    assert len(df) == num_events
    truth = df  # loadgen is_valid was overwritten by computed validity…
    # …so recompute ground truth from the id ranges: roster ids are the
    # valid population, >=100000 ids are the invalid one.
    in_roster = np.isin(df.student_id.to_numpy(np.uint32), roster)
    stored_valid = df.is_valid.to_numpy(bool)
    # no false negatives ever
    assert stored_valid[in_roster].all()
    # false positives bounded (eps=0.01 at far-below-capacity fill)
    fp = stored_valid[~in_roster].mean() if (~in_roster).any() else 0.0
    assert fp <= 0.02, fp

    # HLL counts vs exact uniques per lecture (valid events only)
    vdf = df[stored_valid]
    for day, group in vdf.groupby("lecture_day"):
        exact = group.student_id.nunique()
        est = pipe.count(int(day))
        assert est == pytest.approx(exact, rel=0.05, abs=3)


def test_fused_pipeline_bad_frame_dead_lettered():
    """A poison frame is retried max_redeliveries times, then
    dead-lettered (acked + counted) so the loop terminates instead of
    livelocking on instant broker redelivery."""
    config = Config(transport_backend="memory", max_redeliveries=3)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    producer = client.create_producer(config.pulsar_topic)
    producer.send(b"garbage-not-a-frame")
    pipe.run(idle_timeout_s=0.3)
    assert pipe.metrics.nacked_batches == config.max_redeliveries
    assert pipe.metrics.dead_lettered == 1
    assert pipe.metrics.events == 0
    assert pipe.consumer.backlog() == 0  # poison frame removed from sub


def test_fused_pipeline_bad_frame_does_not_poison_good_ones():
    """Good frames interleaved with a poison frame all process."""
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory", max_redeliveries=2)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(2_000, 500, roster_size=1_000,
                                     num_lectures=2, seed=7)
    frames = list(frames)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    producer.send(frames[0])
    producer.send(b"\x00bad")
    for f in frames[1:]:
        producer.send(f)
    pipe.run(idle_timeout_s=0.3)
    assert pipe.metrics.events == 2_000
    assert pipe.metrics.dead_lettered == 1
    assert pipe.consumer.backlog() == 0


def test_analyzer_reads_columnar_store():
    store = ColumnarEventStore()
    rng = np.random.default_rng(2)
    store.insert_columns(synth_columns(
        rng, 1_000, np.arange(10_000, 10_200, dtype=np.uint32), 4))
    insights = AttendanceAnalyzer(store).generate_insights()
    assert [i["title"] for i in insights][0] == "Habitual Latecomers"
    assert insights[2]["data"]["most_attended"]


def test_fused_get_attendance_stats():
    """Reference get_attendance_stats contract on the fused path
    (reference attendance_processor.py:149-165): HLL unique count +
    that lecture partition's stored records."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000)
    pipe = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                         num_banks=8)
    roster, frames = generate_frames(8_192, 2_048, roster_size=5_000,
                                     num_lectures=3, seed=11)
    pipe.preload(roster)
    producer = pipe.client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(idle_timeout_s=0.2)

    day = pipe.lecture_days()[0]
    stats = pipe.get_attendance_stats(day)
    recs = stats["attendance_records"]
    assert stats["num_records"] == len(recs["student_id"]) > 0
    assert (np.asarray(recs["lecture_day"], np.int64) == day).all()
    valid = np.asarray(recs["is_valid"]).astype(bool)
    exact = len(np.unique(np.asarray(recs["student_id"])[valid]))
    # HLL estimate within its error budget of the exact distinct count.
    assert abs(stats["unique_attendees"] - exact) <= max(3, 0.05 * exact)
    # The reference-style string key answers identically (VERDICT r03
    # weak #7: one key space across both processors).
    s_stats = pipe.get_attendance_stats(f"LECTURE_{day}")
    assert s_stats["unique_attendees"] == stats["unique_attendees"]
    assert s_stats["num_records"] == stats["num_records"]
    assert pipe.count(f"LECTURE_{day}") == pipe.count(day)
    pipe.cleanup()


def test_stats_string_key_unified_across_backends():
    """One event population, BOTH processors, the SAME reference-style
    "LECTURE_YYYYMMDD" query string (reference
    attendance_processor.py:149-165) — the generic SketchStore path and
    the fused path must answer with the same unique-attendee estimate
    scale and the same stored-record count (VERDICT r03 weak #7)."""
    from attendance_tpu.pipeline.events import encode_binary_batch
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.pipeline.processor import AttendanceProcessor

    report = generate_student_data(seed=23, num_students=150,
                                   num_invalid=15)
    roster = np.array(sorted(report.valid_student_ids), np.uint32)

    # Generic processor: JSON wire, its own broker.
    config = Config(bloom_filter_capacity=5_000,
                    transport_backend="memory", sketch_backend="tpu")
    client = MemoryClient(MemoryBroker())
    proc = AttendanceProcessor(config, client=client)
    proc.setup_bloom_filter()
    proc.sketch.bf_add_many(config.bloom_filter_key, roster.tolist())
    producer = client.create_producer(config.pulsar_topic)
    from attendance_tpu.pipeline.events import encode_event
    for e in report.events:
        producer.send(encode_event(e))
    proc.process_attendance(max_events=report.message_count,
                            idle_timeout_s=0.2)

    # Fused pipeline: binary frames, same events.
    fclient = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(Config(bloom_filter_capacity=5_000,
                                transport_backend="memory"),
                         client=fclient, num_banks=8)
    pipe.preload(roster)
    fproducer = fclient.create_producer(pipe.config.pulsar_topic)
    fproducer.send(encode_binary_batch(report.events))
    pipe.run(max_events=report.message_count, idle_timeout_s=0.2)

    lectures = sorted({e.lecture_id for e in report.events
                       if e.lecture_id.startswith("LECTURE_2")})
    assert lectures
    for lecture_id in lectures:
        g = proc.get_attendance_stats(lecture_id)
        f = pipe.get_attendance_stats(lecture_id)
        assert f["num_records"] == len(g["attendance_records"]), lecture_id
        # Two independent HLL backends (different hash domains): equal
        # up to each estimator's error budget around the same exact
        # count, not bit-identical.
        exact = len({e.student_id for e in report.events
                     if e.lecture_id == lecture_id and e.is_valid})
        for est in (g["unique_attendees"], f["unique_attendees"]):
            assert est == pytest.approx(exact, rel=0.05, abs=3), lecture_id
    proc.cleanup()
    pipe.cleanup()


def test_pick_kw_drops_stale_hint():
    """An outlier-wide frame must not permanently disable the 4-byte
    word wire once bank growth makes the hinted width no longer fit."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    pipe = FusedPipeline(Config(transport_backend="memory"),
                         client=MemoryClient(MemoryBroker()), num_banks=256)
    pipe._kw_hint = 23  # outlier frame pinned the hint
    # 256 banks -> 9 bank bits: 23 + 9 == 32 still fits, hint honored
    assert pipe._pick_kw(20, 256) == 23
    # 512 banks -> 10 bits: hint no longer fits but the frame does
    assert pipe._pick_kw(20, 512) == 20
    # frame itself too wide for words: width reported as-is, caller
    # falls back to the byte wire
    assert pipe._pick_kw(30, 512) == 30


def test_competing_fused_pipelines_merge_to_one_answer():
    """The reference's scale-out is competing consumers on one Shared
    subscription against ONE shared Redis (attendance_processor.py:30-34);
    here each consumer owns private HBM sketches, so the union is an
    explicit register-max merge (models.hll.hll_merge) — commutative and
    idempotent, the same collective the mesh uses. Two pipelines split
    one topic's frames; their merged per-day counts and summed validity
    counters must equal a single-consumer run of the same stream."""
    from attendance_tpu.models.hll import hll_merge
    from attendance_tpu.models.hll import (
        best_histogram, estimate_from_histogram)

    num_events, batch = 16_384, 2_048
    roster, frames = generate_frames(num_events, batch, roster_size=6_000,
                                     num_lectures=5, seed=41)
    frames = list(frames)

    def run_single():
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory")
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        pipe.preload(roster)
        prod = client.create_producer(config.pulsar_topic)
        for f in frames:
            prod.send(f)
        pipe.run(max_events=num_events, idle_timeout_s=0.4)
        return pipe

    ref = run_single()
    ref_counts = {d: ref.count(d) for d in ref.lecture_days()}
    ref_vc = ref.validity_counts()

    # Two competing consumers on ONE shared subscription of one broker.
    config = Config(bloom_filter_capacity=20_000,
                    transport_backend="memory")
    broker = MemoryBroker()
    pipes = [FusedPipeline(config, client=MemoryClient(broker),
                           num_banks=8) for _ in range(2)]
    for p in pipes:
        p.preload(roster)
    prod = MemoryClient(broker).create_producer(config.pulsar_topic)
    for f in frames:
        prod.send(f)
    # Alternate consumers so both actually take frames from the shared
    # subscription (single-threaded; each drains a slice of the backlog).
    took = 0
    while took < num_events:
        for p in pipes:
            before = p.metrics.events
            p.run(max_events=before + batch, idle_timeout_s=0.2)
            took += p.metrics.events - before
    assert pipes[0].consumer.backlog() == 0
    assert pipes[0].metrics.events > 0 and pipes[1].metrics.events > 0
    assert (pipes[0].metrics.events + pipes[1].metrics.events
            == num_events)

    # Merged validity counters match the single-consumer run.
    vcs = [p.validity_counts() for p in pipes]
    assert (vcs[0][0] + vcs[1][0], vcs[0][1] + vcs[1][1]) == ref_vc

    # Per-day uniques via explicit register-max union across consumers.
    days = sorted(set(pipes[0].lecture_days())
                  | set(pipes[1].lecture_days()))
    assert days == sorted(ref_counts)
    for day in days:
        rows = []
        for p in pipes:
            bank = p._bank_of.get(day)
            if bank is not None:
                rows.append(p.state.hll_regs[bank])
        merged = rows[0] if len(rows) == 1 else hll_merge(*rows)
        hist = np.asarray(best_histogram(merged[None, :], 14))[0]
        est = int(round(estimate_from_histogram(hist, 14)))
        assert est == ref_counts[day], (day, est, ref_counts[day])


def test_count_all_matches_per_day_counts():
    """count_all (one histogram pass over every bank) must agree with
    per-day count() on both engines."""
    num_events, batch = 8_192, 2_048
    roster, frames = generate_frames(num_events, batch, roster_size=5_000,
                                     num_lectures=6, seed=43)
    frames = list(frames)
    for shards, reps in ((1, 1), (2, 2)):
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory",
                        num_shards=shards, num_replicas=reps)
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        pipe.preload(roster)
        prod = client.create_producer(config.pulsar_topic)
        for f in frames:
            prod.send(f)
        pipe.run(max_events=num_events, idle_timeout_s=0.4)
        batch_counts = pipe.count_all()
        assert set(batch_counts) == set(pipe.lecture_days())
        for day in pipe.lecture_days():
            assert batch_counts[day] == pipe.count(day)
