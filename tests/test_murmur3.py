"""MurmurHash3 unit tests: published vectors + JAX/host agreement."""

import numpy as np

from attendance_tpu.ops import murmur3 as m3


def test_published_vectors_bytes():
    # Well-known MurmurHash3_x86_32 vectors.
    assert m3.murmur3_bytes(b"", 0) == 0x00000000
    assert m3.murmur3_bytes(b"", 1) == 0x514E28B7
    assert m3.murmur3_bytes(b"", 0xFFFFFFFF) == 0x81F16F39
    assert m3.murmur3_bytes(b"\x00\x00\x00\x00", 0) == 0x2362F9DE
    assert m3.murmur3_bytes(b"aaaa", 0x9747B28C) == 0x5A97808A


def test_jax_matches_host_reference():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 2**32, size=4096, dtype=np.uint32)
    for seed in (0, 1, int(m3.SEED_BLOOM_A), int(m3.SEED_HLL_LO)):
        got = np.asarray(m3.murmur3_u32(keys, seed))
        want = np.array(
            [m3.murmur3_u32_host(int(k), seed) for k in keys[:256]],
            dtype=np.uint32)
        np.testing.assert_array_equal(got[:256], want)


def test_avalanche_bit_balance():
    # Each output bit should be ~50% set over sequential integer keys —
    # sequential IDs are exactly the workload (student IDs are small ints,
    # reference data_generator.py:53-54).
    keys = np.arange(1, 1 << 16, dtype=np.uint32)
    h = np.asarray(m3.murmur3_u32(keys, 0))
    for bit in range(32):
        frac = ((h >> bit) & 1).mean()
        assert 0.47 < frac < 0.53, (bit, frac)


def test_seeds_are_independent():
    keys = np.arange(1, 1 << 14, dtype=np.uint32)
    a = np.asarray(m3.murmur3_u32(keys, m3.SEED_BLOOM_A))
    b = np.asarray(m3.murmur3_u32(keys, m3.SEED_BLOOM_B))
    # Collision fraction between differently-seeded hashes ~ 2^-32.
    assert (a == b).mean() < 1e-3
