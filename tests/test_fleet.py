"""Fleet observability plane tests (ISSUE 9).

Covers the pusher -> collector wire (role/instance-labeled merged
exposition, bounded span batches, artifact persistence), federated
trace stitching (the gossip-carried traceparent parenting an
aggregator ``fed_merge`` under the worker's ``fence_publish`` — and
loud tolerance of older frames without the field), ``doctor --fleet``
verdict semantics, the ``fleet`` CLI verb, the ``telemetry --follow``
tail mode, and the ``tools/bench_trend.py`` trajectory gate.
"""

import json
import logging
import sys
import threading
import time
import types
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu.obs.fleet import (
    FleetCollector, FleetPusher, STATUS_FILE, TRACE_FILE)
from attendance_tpu.obs.registry import Registry
from attendance_tpu.obs.tracing import Tracer

REPO = Path(__file__).resolve().parent.parent


def _obs_shim():
    """The (registry, tracer) pair FenceGossip/Aggregator capture —
    per-instance, so one test process can simulate several roles."""
    return types.SimpleNamespace(registry=Registry(), tracer=Tracer())


@pytest.fixture
def collector(tmp_path):
    col = FleetCollector(directory=str(tmp_path / "fleet"),
                         port=0).start()
    yield col
    col.stop()


def _slices(trace_doc):
    return [e for e in trace_doc["traceEvents"] if e.get("ph") == "X"]


# -- pusher -> collector wire ------------------------------------------------

def test_push_merges_roles_with_labels_and_persists(collector,
                                                    tmp_path):
    r1, t1 = Registry(), Tracer(default_role="worker")
    r1.counter("attendance_events_total", help="Events").inc(100)
    t1.add_span("dispatch", 0.0, 0.01, trace_id=t1.new_id())
    r2 = Registry()
    r2.counter("attendance_events_total", help="Events").inc(50)

    p1 = FleetPusher(r1, t1, collector.address, role="worker",
                     instance="w0")
    p2 = FleetPusher(r2, None, collector.address, role="broker",
                     instance="b1")
    assert p1.push_now() and p2.push_now()

    merged = collector.merged_exposition()
    assert ('attendance_events_total{role="worker",instance="w0"} 100'
            in merged)
    assert ('attendance_events_total{role="broker",instance="b1"} 50'
            in merged)
    # Merged text stays VALID exposition: one TYPE line per family,
    # samples grouped under it.
    assert merged.count("# TYPE attendance_events_total counter") == 1

    status = collector.status()
    assert set(status["instances"]) == {"worker@w0", "broker@b1"}
    assert status["instances"]["worker@w0"]["events"] == 100
    assert status["instances"]["worker@w0"]["spans"] == 1

    # Artifacts: per-instance prom files in the FileReporter block
    # format (every existing prom consumer reads them), plus the
    # status + stitched-trace snapshots at stop().
    fleet_dir = tmp_path / "fleet"
    assert (fleet_dir / "worker@w0.prom").exists()
    assert "attendance_events_total 100" in \
        (fleet_dir / "worker@w0.prom").read_text()
    collector.stop()
    assert json.loads((fleet_dir / STATUS_FILE).read_text())["instances"]
    trace = json.loads((fleet_dir / TRACE_FILE).read_text())
    assert [e["name"] for e in _slices(trace)] == ["dispatch"]


def test_push_paces_span_backlog_and_drains_at_stop(collector):
    reg, tracer = Registry(), Tracer()
    for _ in range(1000):
        tracer.add_span("s", 0.0, 0.001, trace_id=1)
    p = FleetPusher(reg, tracer, collector.address, role="worker",
                    instance="w0", span_batch=64)
    # A periodic round ships at most ONE bounded frame — a backlog
    # must pace out over intervals, not park the GIL on one giant
    # serialize.
    assert p.push_now()
    assert collector.status()["instances"]["worker@w0"]["spans"] == 64
    p.stop()  # the stop() path drains everything
    assert collector.status()["instances"]["worker@w0"]["spans"] == 1000


def test_pusher_survives_dead_collector_and_recovers(tmp_path, caplog):
    reg = Registry()
    reg.counter("attendance_events_total", help="e").inc(1)
    col = FleetCollector(port=0)
    addr = col.address
    col.stop()  # never started accepting; the port is dead
    p = FleetPusher(reg, None, addr, role="worker", instance="w0")
    with caplog.at_level(logging.WARNING,
                         logger="attendance_tpu.obs.fleet"):
        assert not p.push_now()
        assert not p.push_now()
    # ONE warning for the outage, not one per interval.
    warns = [r for r in caplog.records if "fleet push" in r.message]
    assert len(warns) == 1
    live = FleetCollector(host="127.0.0.1", port=int(
        addr.rsplit(":", 1)[1])).start()
    try:
        deadline = time.time() + 5
        while not p.push_now():
            assert time.time() < deadline, "pusher never recovered"
        assert "worker@w0" in live.status()["instances"]
    finally:
        p.stop()
        live.stop()


def test_collector_drops_retried_duplicate_frames(collector):
    """resilient_call may re-send a frame whose reply was lost: the
    collector folds each (boot, seq) once, so span batches and push
    counters never double-count — while a RESTARTED pusher (fresh
    boot, seq back at 1) is accepted."""
    from attendance_tpu.transport.framing import enc_props

    rows = json.dumps([["s", "worker", 1, "t", 1.0, 2.0,
                        7, 8, None, None]]).encode()
    hdr = {"role": "worker", "instance": "w0", "kind": "spans",
           "seq": 2, "boot": 10.0, "ts": 1.0}
    body = enc_props(hdr) + rows
    collector._ingest(body)
    collector._ingest(body)  # identical retry: must be dropped
    inst = collector._instances["worker@w0"]
    assert inst.span_count == 1 and inst.pushes == 1
    # A restarted pusher's fresh boot resets the window.
    body2 = enc_props({**hdr, "seq": 1, "boot": 11.0}) + rows
    collector._ingest(body2)
    assert inst.span_count == 2 and inst.pushes == 2


def test_collector_rejects_malformed_push_keeps_serving(collector):
    import socket as socket_mod

    from attendance_tpu.transport.framing import recv_frame, send_frame

    host, port = collector.address.rsplit(":", 1)
    with socket_mod.create_connection((host, int(port))) as sock:
        send_frame(sock, 1, b"\x00garbage")
        status, reply = recv_frame(sock)
        assert status != 0 and reply
    reg = Registry()
    p = FleetPusher(reg, None, collector.address, role="w",
                    instance="i")
    assert p.push_now()  # the collector still accepts good pushes


def test_fleet_routes_on_metrics_server(collector):
    import urllib.request

    from attendance_tpu.obs.exposition import MetricsServer

    reg = Registry()
    reg.counter("attendance_events_total", help="e").inc(9)
    p = FleetPusher(reg, None, collector.address, role="serve",
                    instance="s0")
    assert p.push_now()
    server = MetricsServer(reg, port=0).start()
    try:
        collector.attach(server)
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(base + "/fleet/metrics",
                                      timeout=5).read().decode()
        assert 'attendance_events_total{role="serve"' in body
        doc = json.loads(urllib.request.urlopen(
            base + "/fleet/status", timeout=5).read())
        assert "serve@s0" in doc["instances"]
        trace = json.loads(urllib.request.urlopen(
            base + "/fleet/trace", timeout=5).read())
        assert trace["otherData"]["stitched"] is True
        collector.detach(server)
        with pytest.raises(Exception):
            urllib.request.urlopen(base + "/fleet/status", timeout=5)
    finally:
        server.stop()


# -- federated trace stitching -----------------------------------------------

def _worker_state(precision=14):
    regs = np.zeros((1, 1 << precision), np.uint8)
    regs[0, :4] = 3
    counts = np.array([[7, 0], [1, 0]], np.uint32)
    return regs, counts


def test_gossip_traceparent_stitches_fed_merge_under_fence(
        collector, tmp_path):
    from attendance_tpu.config import Config
    from attendance_tpu.federation.gossip import Aggregator, FenceGossip
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    broker = MemoryBroker()
    wobs, aobs = _obs_shim(), _obs_shim()
    cfg = Config(fed_worker="w0", fed_shard=0,
                 snapshot_dir=str(tmp_path / "chain"))
    gossip = FenceGossip(cfg, client=MemoryClient(broker), obs=wobs)
    agg = Aggregator(client=MemoryClient(broker),
                     topic=gossip.topic, num_shards=1,
                     dead_after_s=30.0, obs=aobs)
    try:
        regs, counts = _worker_state()
        assert gossip.publish_full(None, regs, counts, {0: 0}, 7)
        deadline = time.time() + 10
        while agg.poll(timeout_ms=100) == 0:
            assert time.time() < deadline, "frame never folded"
    finally:
        gossip.close()
        agg.stop()

    # Ship both roles' spans to the collector and stitch.
    FleetPusher(wobs.registry, wobs.tracer, collector.address,
                role="worker", instance="w0").push_now(drain=True)
    FleetPusher(aobs.registry, aobs.tracer, collector.address,
                role="aggregator", instance="agg").push_now(drain=True)
    slices = _slices(collector.export_trace())
    fences = {e["args"]["span_id"]: e for e in slices
              if e["name"] == "fence_publish"}
    merges = [e for e in slices if e["name"] == "fed_merge"]
    assert fences and merges
    for m in merges:
        assert m["args"]["parent_span_id"] in fences
        parent = fences[m["args"]["parent_span_id"]]
        assert m["args"]["trace_id"] == parent["args"]["trace_id"]


def test_aggregator_tolerates_frames_without_traceparent(caplog):
    """An OLDER worker's frames lack the header key entirely: the fold
    must proceed normally, the merge span must degrade to a fresh
    root, and the aggregator says so ONCE per worker."""
    import struct

    from attendance_tpu.federation.frames import (
        FRAME_VERSION, encode_frame)
    from attendance_tpu.federation.gossip import Aggregator
    from attendance_tpu.transport.framing import dec_props, enc_props
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    regs, counts = _worker_state()
    data = encode_frame(
        worker="old", kind="full", incarnation=1.0, seq=1, shard=0,
        fence_ts=time.time(), events=7, bank_of={0: 0},
        arrays={"regs": regs, "counts": counts})
    header, off = dec_props(data, 2)
    assert header.pop("traceparent") == ""  # current build carries it
    old_frame = (struct.pack("<H", FRAME_VERSION) + enc_props(header)
                 + data[off:])

    broker = MemoryBroker()
    aobs = _obs_shim()
    agg = Aggregator(client=MemoryClient(broker), topic="g",
                     num_shards=1, dead_after_s=30.0, obs=aobs)
    producer = MemoryClient(broker).create_producer("g")
    try:
        with caplog.at_level(
                logging.WARNING,
                logger="attendance_tpu.federation.gossip"):
            producer.send(old_frame)
            header["seq"] = 2
            producer.send(struct.pack("<H", FRAME_VERSION)
                          + enc_props(header) + data[off:])
            deadline = time.time() + 10
            folded = 0
            while folded < 2:
                folded += agg.poll(timeout_ms=100)
                assert time.time() < deadline
        assert agg.view.events == 7  # both frames folded normally
        warns = [r for r in caplog.records
                 if "no traceparent" in r.message]
        assert len(warns) == 1  # once per worker, not per frame
        merges = [s for s in aobs.tracer.snapshot()
                  if s.name == "fed_merge"]
        assert merges and all(m.parent_id is None for m in merges)
    finally:
        agg.stop()


# -- doctor --fleet ----------------------------------------------------------

def _write_fleet_dir(root: Path, lag_pairs=None, staleness=None,
                     firing=0):
    root.mkdir(parents=True, exist_ok=True)
    worker = ["attendance_events_total 1000",
              f"attendance_slo_firing{{slo=\"x\"}} {firing}"]
    if staleness is not None:
        worker.append(
            f"attendance_read_staleness_seconds {staleness}")
    (root / "worker@w0.prom").write_text("\n".join(worker) + "\n")
    agg = ["attendance_events_total 1000"]
    if lag_pairs:
        agg.append("# TYPE attendance_fed_merge_lag_seconds histogram")
        agg += ['attendance_fed_merge_lag_seconds_bucket{le="%s"} %d'
                % (le, c) for le, c in lag_pairs]
    (root / "aggregator@agg.prom").write_text("\n".join(agg) + "\n")


def test_doctor_fleet_one_table_with_fleet_rows(tmp_path):
    from attendance_tpu.obs.slo import doctor_fleet_report

    _write_fleet_dir(tmp_path / "fleet",
                     lag_pairs=[(0.008, 9), (1.024, 10), ("+Inf", 10)],
                     staleness=0.5)
    text, ok = doctor_fleet_report(str(tmp_path / "fleet"),
                                   merge_lag_ceiling=2.0,
                                   staleness_ceiling=1.0)
    assert ok
    assert "worker@w0:" in text and "aggregator@agg:" in text
    assert "fleet: merge lag p99" in text
    assert "fleet: worst read staleness" in text
    assert "fleet: events (sum over roles)" in text and "2000" in text

    # Breaches gate: lag p99 above the ceiling / staleness above.
    text, ok = doctor_fleet_report(str(tmp_path / "fleet"),
                                   merge_lag_ceiling=0.001)
    assert not ok and "FAIL" in text
    text, ok = doctor_fleet_report(str(tmp_path / "fleet"),
                                   staleness_ceiling=0.1)
    assert not ok

    # A merge-lag ceiling with NO lag histogram anywhere must fail
    # loudly, not pass vacuously.
    _write_fleet_dir(tmp_path / "bare")
    text, ok = doctor_fleet_report(str(tmp_path / "bare"),
                                   merge_lag_ceiling=2.0)
    assert not ok and "fleet: merge lag p99" in text

    # Alerts firing in ANY role fail the fleet.
    _write_fleet_dir(tmp_path / "firing", firing=1)
    text, ok = doctor_fleet_report(str(tmp_path / "firing"))
    assert not ok and "firing across roles" in text


def test_doctor_fleet_cli_exit_codes(tmp_path):
    from attendance_tpu.cli import main

    _write_fleet_dir(tmp_path / "fleet",
                     lag_pairs=[(0.008, 10), ("+Inf", 10)])
    with pytest.raises(SystemExit) as e:
        main(["doctor", "--fleet", str(tmp_path / "fleet"),
              "--merge-lag-ceiling", "2.0"])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        main(["doctor", "--fleet", str(tmp_path / "fleet"),
              "--merge-lag-ceiling", "0.001"])
    assert e.value.code == 1
    with pytest.raises(SystemExit) as e:
        main(["doctor", "--fleet", str(tmp_path / "nope")])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        (tmp_path / "empty").mkdir()
        main(["doctor", "--fleet", str(tmp_path / "empty")])
    assert e.value.code == 2


# -- fleet CLI verb + telemetry --follow -------------------------------------

def test_fleet_verb_snapshot_json_from_dir(tmp_path, capsys):
    from attendance_tpu.cli import main

    col = FleetCollector(directory=str(tmp_path / "fleet"), port=0
                         ).start()
    reg = Registry()
    reg.counter("attendance_events_total", help="e").inc(3)
    FleetPusher(reg, None, col.address, role="worker",
                instance="w0").push_now()
    col.stop()
    out = tmp_path / "snap.json"
    main(["fleet", "--dir", str(tmp_path / "fleet"),
          "--snapshot-json", str(out)])
    doc = json.loads(out.read_text())
    assert doc["instances"]["worker@w0"]["events"] == 3
    capsys.readouterr()
    main(["fleet", "--dir", str(tmp_path / "fleet")])
    table = capsys.readouterr().out
    assert "worker@w0" in table and "role@instance" in table


def test_telemetry_follow_rerenders_on_append(tmp_path, capsys):
    from attendance_tpu.cli import _follow_file
    from attendance_tpu.obs.exposition import render

    path = tmp_path / "live.prom"
    reg = Registry()
    c = reg.counter("attendance_events_total", help="e")
    c.inc(5)
    path.write_text("# scrape 1.0\n" + render(reg))

    appended = threading.Event()

    def append_later():
        time.sleep(0.3)
        c.inc(10)
        with open(path, "a") as f:
            f.write("# scrape 2.0\n" + render(reg))
        appended.set()

    t = threading.Thread(target=append_later)
    t.start()
    renders = _follow_file(str(path), last=32, interval_s=0.05,
                           max_rounds=40)
    t.join()
    assert appended.is_set()
    assert renders >= 2  # initial render + at least the appended block
    out = capsys.readouterr().out
    assert out.count("== ") == renders
    assert "15" in out  # the follow shows the LATEST block


def test_telemetry_verb_follow_flag(tmp_path, capsys):
    """--follow on a missing file renders nothing and exits cleanly
    when bounded (the CLI loop is the same helper, unbounded)."""
    from attendance_tpu.cli import _follow_file

    renders = _follow_file(str(tmp_path / "never.prom"), last=8,
                           interval_s=0.01, max_rounds=3)
    assert renders == 0


# -- bench trend gate --------------------------------------------------------

HOST_A = {"cpu_count": 4, "device_kind": "cpu",
          "device_platform": "cpu", "num_devices": 1}
HOST_B = {"cpu_count": 96, "device_kind": "TPU v4",
          "device_platform": "tpu", "num_devices": 4}


def _write_bench(root: Path, name: str, value: float, host=None,
                 metric="e2e_pipeline_throughput", **extra):
    doc = {"metric": metric, "value": value, "unit": "events/sec",
           **extra}
    if host is not None:
        doc["host"] = host
    (root / name).write_text(json.dumps(doc))


def _trend():
    sys.path.insert(0, str(REPO / "tools"))
    import bench_trend
    return bench_trend


def test_trend_gate_passes_on_committed_artifacts():
    bt = _trend()
    rc = bt.main(["--dir", str(REPO)])
    assert rc == 0


def test_trend_gate_fails_on_like_host_regression(tmp_path):
    bt = _trend()
    _write_bench(tmp_path, "BENCH_r01.json", 100e6, host=HOST_A,
                 socket_events_per_sec=50e6)
    _write_bench(tmp_path, "BENCH_r02.json", 101e6, host=HOST_A,
                 socket_events_per_sec=44e6)  # -12% on a like host
    assert bt.main(["--dir", str(tmp_path)]) == 1
    # A generous ceiling lets the same trajectory pass.
    assert bt.main(["--dir", str(tmp_path),
                    "--max-regression", "0.2"]) == 0


def test_trend_gate_exact_threshold_regression_fails(tmp_path):
    bt = _trend()
    _write_bench(tmp_path, "BENCH_r01.json", 100e6, host=HOST_A)
    _write_bench(tmp_path, "BENCH_r02.json", 90e6, host=HOST_A)
    assert bt.main(["--dir", str(tmp_path)]) == 1  # >=10% gates


def test_trend_gate_skips_cross_host_and_unfingerprinted(tmp_path,
                                                         capsys):
    bt = _trend()
    _write_bench(tmp_path, "BENCH_r01.json", 100e6, host=HOST_A)
    _write_bench(tmp_path, "BENCH_r02.json", 40e9, host=HOST_B)
    _write_bench(tmp_path, "BENCH_r03.json", 10e6)  # no fingerprint
    _write_bench(tmp_path, "BENCH_FED_r08.json", 1e6, host=HOST_A,
                 metric="federation_aggregate_events_per_sec")
    assert bt.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "skipped (host changed)" in out
    assert "skipped (unfingerprinted)" in out
    assert "single artifact" in out


def test_trend_gate_regression_spanning_skipped_artifact_gates(
        tmp_path):
    """An unfingerprinted artifact in the middle of a series must not
    shield a like-for-like regression spanning it: the gate walks back
    to the newest comparable predecessor."""
    bt = _trend()
    _write_bench(tmp_path, "BENCH_r01.json", 100e6, host=HOST_A)
    _write_bench(tmp_path, "BENCH_r02.json", 95e6)  # no fingerprint
    _write_bench(tmp_path, "BENCH_r03.json", 70e6, host=HOST_A)
    assert bt.main(["--dir", str(tmp_path)]) == 1  # r01 vs r03: -30%
    # The same middle artifact with NO comparable predecessor anywhere
    # stays a visible skip, not a gate.
    (tmp_path / "BENCH_r01.json").unlink()
    assert bt.main(["--dir", str(tmp_path)]) == 0


def test_trend_gate_series_are_independent(tmp_path):
    """A FED-series regression must not be compared against the E2E
    series, and vice versa."""
    bt = _trend()
    _write_bench(tmp_path, "BENCH_r01.json", 100e6, host=HOST_A)
    _write_bench(tmp_path, "BENCH_FED_r01.json", 1e6, host=HOST_A,
                 metric="federation_aggregate_events_per_sec")
    _write_bench(tmp_path, "BENCH_FED_r02.json", 0.5e6, host=HOST_A,
                 metric="federation_aggregate_events_per_sec")
    assert bt.main(["--dir", str(tmp_path)]) == 1
    (tmp_path / "BENCH_FED_r02.json").unlink()
    assert bt.main(["--dir", str(tmp_path)]) == 0
