"""Segmented bit-packed wire: pack parity, step parity, e2e equivalence.

The seg wire (models.fused.fused_step_seg) carries kb bits/event with
events counting-sorted by HLL bank and the bank ids reconstructed on
device from segment boundaries — the narrowest host->device transfer
the fused pipeline has. These tests pin:
  * the native C packer (hostpipe.c atp_pack_seg) against the numpy
    reference packer, bit for bit, including strided ATB1 inputs and
    LUT-miss reporting;
  * the seg device step against the canonical fused_step on identical
    event sets (same Bloom/HLL/counter state, permuted validity);
  * FusedPipeline equivalence across wire formats end to end (same
    store contents, same counts), including frames with duplicate
    primary keys (the stable sort must keep last-write-wins ties in
    append order) and out-of-LUT-window hashed lecture days (the
    native bypass / numpy fallback path).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from attendance_tpu.config import Config
from attendance_tpu.models.bloom import bloom_add_packed
from attendance_tpu.models.fused import (
    delta_scan, fused_step, init_state, make_jitted_step_delta,
    make_jitted_step_seg, pack_delta, pack_seg, seg_buf_words)
from attendance_tpu.native import load as load_native
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


def test_pack_seg_native_matches_numpy():
    nat = load_native()
    if nat is None:
        pytest.skip("native host runtime unavailable")
    rng = np.random.default_rng(1)
    day_base = 20250100
    for trial in range(20):
        n = int(rng.integers(1, 3000))
        padded = 1 << int(np.ceil(np.log2(max(n, 256))))
        num_banks = int(rng.integers(1, 40))
        kb = int(rng.integers(11, 33))
        keys = rng.integers(0, 1 << kb, n,
                            dtype=np.uint64).astype(np.uint32)
        banks = rng.integers(0, num_banks, n).astype(np.int32)
        days = (day_base + banks).astype(np.uint32)
        lut = np.full(16384, -1, np.int32)
        lut[:num_banks] = np.arange(num_banks)
        buf_c, perm_c, miss = nat.pack_seg(keys, days, lut, day_base,
                                           kb, padded, num_banks)
        assert miss == -1
        buf_np, perm_np = pack_seg(keys, banks, kb, padded, num_banks)
        assert len(buf_np) == seg_buf_words(num_banks, kb, padded)
        np.testing.assert_array_equal(perm_c, perm_np)
        np.testing.assert_array_equal(buf_c, buf_np)


def test_pack_seg_native_strided_and_miss():
    nat = load_native()
    if nat is None:
        pytest.skip("native host runtime unavailable")
    rng = np.random.default_rng(2)
    day_base = 20250100
    lut = np.full(256, -1, np.int32)
    lut[:8] = np.arange(8)
    n = 1000
    rec = np.zeros(n, dtype=np.dtype(
        [("sid", "<u4"), ("day", "<u4"), ("pad", "V12")]))
    rec["sid"] = rng.integers(0, 1 << 20, n)
    rec["day"] = day_base + rng.integers(0, 8, n)
    buf_c, perm_c, miss = nat.pack_seg(rec["sid"], rec["day"], lut,
                                       day_base, 20, 1024, 8)
    assert miss == -1
    banks = (rec["day"].astype(np.int64) - day_base).astype(np.int32)
    buf_np, perm_np = pack_seg(np.ascontiguousarray(rec["sid"]), banks,
                               20, 1024, 8)
    np.testing.assert_array_equal(buf_c, buf_np)
    np.testing.assert_array_equal(perm_c, perm_np)
    # LUT miss: reported at the first offending index.
    days_bad = rec["day"][:50].copy()
    days_bad[37] = day_base + 9999
    _, _, miss = nat.pack_seg(np.ascontiguousarray(rec["sid"][:50]),
                              days_bad, lut, day_base, 20, 256, 8)
    assert miss == 37


def test_native_pack_overflow_signalling():
    """A key wider than the requested width must be reported (miss ==
    -3), never silently packed corrupt — the dispatchers trust the
    monotonic width hint and rely on this signal to rescan."""
    nat = load_native()
    if nat is None:
        pytest.skip("native host runtime unavailable")
    day_base = 20250100
    lut = np.full(256, -1, np.int32)
    lut[:8] = np.arange(8)
    keys = np.array([100, 5000, 70000], np.uint32)  # 70000: 17 bits
    days = np.full(3, day_base, np.uint32)
    words, miss = nat.pack_words(keys, days, lut, day_base, 10, 256)
    assert words is None and miss == -3
    words, miss = nat.pack_words(keys, days, lut, day_base, 17, 256)
    assert miss == -1 and (words[:3] == keys).all()
    buf, perm, miss = nat.pack_seg(keys, days, lut, day_base, 10, 256, 8)
    assert buf is None and miss == -3
    _, _, miss = nat.pack_seg(keys, days, lut, day_base, 17, 256, 8)
    assert miss == -1
    # A LUT miss aborts at its index before the overflow verdict.
    days_bad = days.copy()
    days_bad[1] = day_base + 99
    _, miss = nat.pack_words(keys, days_bad, lut, day_base, 10, 256)
    assert miss == 1


@pytest.mark.parametrize("kb", [17, 22, 32])
def test_seg_step_matches_fused_step(kb):
    rng = np.random.default_rng(kb)
    state, params = init_state(capacity=5000, num_banks=16)
    roster = rng.choice(1 << min(kb, 17), 3000,
                        replace=False).astype(np.uint32)
    bits = bloom_add_packed(state.bloom_bits, jnp.asarray(roster), params)
    state = state._replace(bloom_bits=bits)
    state_seg = state._replace(bloom_bits=jnp.array(np.asarray(bits)))

    n, padded = 700, 1024
    keys = np.where(rng.random(n) < 0.5, rng.choice(roster, n),
                    rng.integers(0, 1 << kb, n,
                                 dtype=np.uint64)).astype(np.uint32)
    banks = rng.integers(0, 16, n).astype(np.int32)

    mask = np.zeros(padded, bool)
    mask[:n] = True
    k_pad = np.zeros(padded, np.uint32)
    k_pad[:n] = keys
    b_pad = np.full(padded, -1, np.int32)
    b_pad[:n] = banks
    sref, vref = fused_step(state, jnp.asarray(k_pad),
                            jnp.asarray(b_pad), jnp.asarray(mask), params)

    buf, perm = pack_seg(keys, banks, kb, padded, 16)
    step = make_jitted_step_seg(params, kb, padded, 16)
    sseg, vseg = step(state_seg, jnp.asarray(buf))

    np.testing.assert_array_equal(np.asarray(sref.hll_regs),
                                  np.asarray(sseg.hll_regs))
    np.testing.assert_array_equal(np.asarray(sref.counts),
                                  np.asarray(sseg.counts))
    np.testing.assert_array_equal(np.asarray(vref)[:n][perm],
                                  np.asarray(vseg)[:n])


def test_pack_delta_native_matches_numpy():
    nat = load_native()
    if nat is None:
        pytest.skip("native host runtime unavailable")
    rng = np.random.default_rng(9)
    day_base = 20250100
    for trial in range(20):
        n = int(rng.integers(1, 3000))
        padded = 1 << int(np.ceil(np.log2(max(n, 256))))
        num_banks = int(rng.integers(1, 40))
        kb = int(rng.integers(8, 33))
        keys = rng.integers(0, 1 << kb, n,
                            dtype=np.uint64).astype(np.uint32)
        banks = rng.integers(0, num_banks, n).astype(np.int32)
        days = (day_base + banks).astype(np.uint32)
        lut = np.full(16384, -1, np.int32)
        lut[:num_banks] = np.arange(num_banks)
        buf_c, perm_c, db, needed_c, miss = nat.pack_delta(
            keys, days, lut, day_base, 1, padded, num_banks)
        assert miss == -1
        *_, needed = delta_scan(keys, banks, num_banks)
        assert needed_c == needed
        assert needed <= db <= 32
        buf_np, perm_np = pack_delta(keys, banks, db, padded, num_banks)
        np.testing.assert_array_equal(perm_c, perm_np)
        np.testing.assert_array_equal(buf_c, buf_np)
    # equal (bank, key) events keep append order (dedup tie contract)
    keys = np.array([5, 5, 5, 9, 5], np.uint32)
    days = np.full(5, day_base, np.uint32)
    _, perm_c, _, _, miss = nat.pack_delta(keys, days, lut, day_base,
                                           1, 256, 1)
    assert miss == -1 and list(perm_c) == [0, 1, 2, 4, 3]


@pytest.mark.parametrize("kb", [17, 22])
def test_delta_step_matches_fused_step(kb):
    rng = np.random.default_rng(100 + kb)
    state, params = init_state(capacity=5000, num_banks=16)
    roster = rng.choice(1 << min(kb, 17), 3000,
                        replace=False).astype(np.uint32)
    bits = bloom_add_packed(state.bloom_bits, jnp.asarray(roster), params)
    state = state._replace(bloom_bits=bits)
    state_d = state._replace(bloom_bits=jnp.array(np.asarray(bits)))

    n, padded = 700, 1024
    keys = np.where(rng.random(n) < 0.5, rng.choice(roster, n),
                    rng.integers(0, 1 << kb, n,
                                 dtype=np.uint64)).astype(np.uint32)
    banks = rng.integers(0, 16, n).astype(np.int32)

    mask = np.zeros(padded, bool)
    mask[:n] = True
    k_pad = np.zeros(padded, np.uint32)
    k_pad[:n] = keys
    b_pad = np.full(padded, -1, np.int32)
    b_pad[:n] = banks
    sref, vref = fused_step(state, jnp.asarray(k_pad),
                            jnp.asarray(b_pad), jnp.asarray(mask), params)

    *_, needed = delta_scan(keys, banks, 16)
    buf, perm = pack_delta(keys, banks, needed, padded, 16)
    step = make_jitted_step_delta(params, needed, padded, 16)
    sdel, vdel = step(state_d, jnp.asarray(buf))

    np.testing.assert_array_equal(np.asarray(sref.hll_regs),
                                  np.asarray(sdel.hll_regs))
    np.testing.assert_array_equal(np.asarray(sref.counts),
                                  np.asarray(sdel.counts))
    np.testing.assert_array_equal(np.asarray(vref)[:n][perm],
                                  np.asarray(vdel)[:n])


def _run_pipeline(wire_format: str, frames, roster, num_events: int):
    config = Config(bloom_filter_capacity=50_000,
                    transport_backend="memory", wire_format=wire_format)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.5)
    assert pipe.consumer.backlog() == 0
    return pipe


def test_pipeline_equivalent_across_wires():
    """seg and word wires must be observationally identical end to end:
    same deduped store rows, same device counters, same per-day HLL
    counts — on the same frame stream."""
    num_events, batch = 20_000, 2_048
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=10_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=11)
    frames = list(frames)
    pipes = {w: _run_pipeline(w, frames, roster, num_events)
             for w in ("word", "seg", "delta")}
    dfs = {w: p.store.to_dataframe().sort_values(
        ["lecture_day", "micros", "student_id"]).reset_index(drop=True)
        for w, p in pipes.items()}
    for w in ("seg", "delta"):
        assert dfs["word"].equals(dfs[w])
        assert (pipes["word"].validity_counts()
                == pipes[w].validity_counts())
        assert pipes["word"].lecture_days() == pipes[w].lecture_days()
        for day in pipes["word"].lecture_days():
            assert pipes["word"].count(day) == pipes[w].count(day)


def test_seg_wire_dedup_ties_keep_append_order():
    """Duplicate primary keys inside one frame: the seg wire's stable
    bank sort must preserve last-write-wins exactly (same day -> same
    bank -> same relative order)."""
    from attendance_tpu.pipeline.loadgen import frame_from_columns

    cols = {
        "student_id": np.array([7, 7, 8, 7], np.uint32),
        "lecture_day": np.array([20260101] * 4, np.uint32),
        "micros": np.array([100, 100, 100, 100], np.int64),
        "is_valid": np.array([True, True, True, True]),
        "event_type": np.array([0, 1, 0, 1], np.int8),
    }
    frame = frame_from_columns(cols)
    roster = np.array([7, 8], np.uint32)
    for wire in ("word", "seg", "delta"):
        pipe = _run_pipeline(wire, [frame], roster, 4)
        df = pipe.store.to_dataframe()  # deduped: 2 rows
        assert len(df) == 2
        # Last write wins: student 7's surviving row is the LAST
        # appended one (event_type exit).
        assert int(df[df.student_id == 7].event_type.item()) == 1


def test_delta_width_hint_decays_after_outlier():
    """One frame with huge sorted-key gaps must not pin the delta wire
    wide forever: after 16 consecutive narrow frames the width hint
    drops back to what the recent population needs."""
    from attendance_tpu.pipeline.loadgen import frame_from_columns

    def frame(keys):
        n = len(keys)
        return frame_from_columns({
            "student_id": np.asarray(keys, np.uint32),
            "lecture_day": np.full(n, 20260101, np.uint32),
            "micros": np.arange(n, dtype=np.int64),
            "is_valid": np.ones(n, bool),
            "event_type": np.zeros(n, np.int8),
        })

    rng = np.random.default_rng(13)
    wide = rng.choice(1 << 22, 300, replace=False).astype(np.uint32)
    narrow = (10_000 + rng.choice(2_000, 300,
                                  replace=False)).astype(np.uint32)
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory", wire_format="delta")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=4)
    pipe.preload(narrow)
    producer = client.create_producer(config.pulsar_topic)
    producer.send(frame(wide))
    pipe.run(max_events=300, idle_timeout_s=0.5)
    wide_hint = pipe._db_hint
    assert wide_hint >= 10  # 300 keys over 2^22: double-digit gaps
    for _ in range(20):
        producer.send(frame(narrow))
    pipe.run(max_events=300 * 21, idle_timeout_s=0.5)
    assert pipe._db_hint < wide_hint
    # Accuracy unaffected throughout: every narrow-roster event valid.
    sv = np.asarray(pipe.store.to_columns(deduplicate=False)["is_valid"])
    assert sv[300:].all()


def test_fuzzed_binary_frames_dead_letter_cleanly():
    """Randomly corrupted/truncated binary frames interleaved with good
    ones: every corrupt frame must dead-letter (never crash, never
    livelock) and every good frame must still process — on every wire."""

    rng = np.random.default_rng(21)
    roster, frames = generate_frames(4096, 512, roster_size=2_000,
                                     num_lectures=4, seed=2)
    frames = list(frames)
    bad = []
    for f in frames[:4]:
        buf = bytearray(f)
        kind = rng.integers(0, 3)
        if kind == 0:
            buf = buf[:int(rng.integers(1, len(buf)))]  # truncation
        elif kind == 1:
            # Bit flips. ATB2 carries no checksum — payload-only
            # corruption decodes cleanly — so the magic is corrupted
            # LAST (random payload flips first, which could otherwise
            # cancel a same-index header flip) to make the frame
            # reliably undecodable.
            for _ in range(7):
                buf[int(rng.integers(8, min(64, len(buf))))] ^= 0xFF
            buf[0] ^= 0xFF
        else:
            buf = bytearray(b"\x00" * int(rng.integers(1, 40)))
        bad.append(bytes(buf))

    for wire in ("word", "seg", "delta"):
        config = Config(bloom_filter_capacity=10_000,
                        transport_backend="memory", wire_format=wire,
                        max_redeliveries=1)
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=4)
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for good, corrupt in zip(frames, bad + [None] * len(frames)):
            if corrupt is not None:
                producer.send(corrupt)
            producer.send(good)
        pipe.run(max_events=4096, idle_timeout_s=1.0)
        assert pipe.metrics.events == 4096, wire
        # The run can hit max_events with poison redeliveries still
        # queued; a drain pass must dead-letter them all and leave the
        # subscription clean.
        pipe.run(idle_timeout_s=1.0)
        assert pipe.consumer.backlog() == 0, wire
        assert pipe.metrics.dead_lettered == len(bad), wire
        df = pipe.store.to_dataframe(deduplicate=False)
        assert len(df) == 4096, wire


def test_auto_wire_ladder_adapts_to_backpressure():
    """The adaptive ladder must climb (narrower wire) under sustained
    full-deque backpressure, descend under sustained drain, clamp at
    both ends, and freeze while checkpointing."""
    config = Config(transport_backend="memory", wire_format="auto")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)

    def drive(frames, waited=False, depth=4):
        """Simulate `frames` frames: `waited` = the hot loop blocked on
        a full deque since the last frame (the climb signal); `depth` =
        deque depth at dispatch (<=1 is the descend signal)."""
        pipe._inflight.clear()
        pipe._inflight.extend([(None, None)] * depth)
        out = []
        for _ in range(frames):
            pipe._drain_waited = waited
            out.append(pipe._auto_wire())
        return out

    assert pipe._auto_level == 0
    # Two forced-wait signals climb one level; sustained pressure tops
    # out at the ladder's end and stays clamped there.
    seen = drive(2, waited=True)
    assert pipe._auto_level == 1 and seen[-1] == "seg"
    drive(20, waited=True)
    assert pipe._auto_level == 2 and drive(1, waited=True) == ["delta"]
    # Descent needs six drained-empty signals per level, clamps at word.
    seen = drive(5, depth=0)
    assert pipe._auto_level == 2  # not yet
    drive(30, depth=0)
    assert pipe._auto_level == 0 and drive(1, depth=0) == ["word"]
    # Mid-depth frames with no forced wait are neutral: no drift.
    pipe._auto_level, pipe._auto_pressure = 1, 0
    drive(50, depth=4)
    assert pipe._auto_level == 1
    # Checkpointing freezes adaptation at the current level.
    pipe._snap_dir = object()
    assert drive(10, waited=True, depth=8) == ["seg"] * 10
    assert pipe._auto_level == 1 and pipe._auto_pressure == 0


def test_seg_wire_out_of_window_days_fall_back():
    """Hashed non-calendar lecture days live outside the dense LUT
    window; auto mode must still process them correctly (native bypass
    falls back to the legacy wires / numpy packer)."""
    from attendance_tpu.pipeline.loadgen import frame_from_columns

    rng = np.random.default_rng(5)
    n = 512
    roster = np.arange(10_000, 12_000, dtype=np.uint32)
    cols = {
        "student_id": rng.choice(roster, n).astype(np.uint32),
        # One calendar day plus one hash-range day far outside the LUT
        # window relative to it.
        "lecture_day": np.where(rng.random(n) < 0.5, 20260101,
                                100_000_777).astype(np.uint32),
        "micros": np.arange(n, dtype=np.int64),
        "is_valid": np.ones(n, bool),
        "event_type": np.zeros(n, np.int8),
    }
    frame = frame_from_columns(cols)
    for wire in ("auto", "seg", "delta"):
        pipe = _run_pipeline(wire, [frame], roster, n)
        assert pipe.metrics.events == n
        df = pipe.store.to_dataframe(deduplicate=False)
        assert len(df) == n
        assert bool(df.is_valid.all())  # whole roster preloaded
        assert sorted(pipe.lecture_days()) == [20260101, 100_000_777]


def test_pack_seg_numpy_rejects_overflowing_keys():
    """A key wider than kb bits must raise, not OR-spill into the next
    lane's bitstream (ADVICE r02: mirror the native packer's rc=-3 and
    pack_delta's needed>db refusal)."""
    import pytest

    keys = np.array([5, 1 << 20, 9], dtype=np.uint32)  # 21-bit key
    banks = np.zeros(3, dtype=np.int32)
    with pytest.raises(ValueError, match="width"):
        pack_seg(keys, banks, kb=10, padded=256, num_banks=4)
    # Deriving kb from the frame's own max key always succeeds.
    buf, perm = pack_seg(keys, banks, kb=21, padded=256, num_banks=4)
    assert buf is not None and len(perm) == 3
