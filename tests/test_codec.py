"""Codec-seam tests (ISSUE 6): every wire round-trips through the
extracted decode -> assemble -> dispatch interface identically to the
pre-refactor paths, and the vectorized batch scanner is differentially
identical to the exact Python codec on every payload shape."""

import numpy as np
import pytest

from attendance_tpu.pipeline import codec
from attendance_tpu.pipeline.events import (
    AttendanceEvent, columns_from_events, decode_binary_batch,
    decode_event_batch, decode_json_batch_columns, encode_binary_batch,
    encode_event, encode_planar_batch)
from attendance_tpu.pipeline.loadgen import frame_from_columns, synth_columns

COLS = ("student_id", "lecture_day", "micros", "is_valid", "event_type")


def _events(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return [AttendanceEvent(
        student_id=int(rng.integers(1, 1 << 31)),
        timestamp=f"2026-07-{1 + int(rng.integers(0, 28)):02d}"
                  f"T{int(rng.integers(0, 24)):02d}"
                  f":{int(rng.integers(0, 60)):02d}"
                  f":{int(rng.integers(0, 60)):02d}",
        lecture_id=f"LECTURE_202607{1 + int(rng.integers(0, 28)):02d}",
        is_valid=bool(rng.random() < 0.9),
        event_type="exit" if rng.random() < 0.5 else "entry")
        for _ in range(n)]


def _assert_cols_equal(a, b, keys=COLS):
    for k in keys:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


# ---------------------------------------------------------------------------
# Round-trip identity vs the pre-refactor paths
# ---------------------------------------------------------------------------

def test_json_codec_matches_legacy_decode():
    payloads = [encode_event(e) for e in _events()]
    seam = codec.get_codec("json").decode(payloads)
    legacy = decode_json_batch_columns(payloads)
    _assert_cols_equal(seam, legacy)


def test_json_codec_vector_engine_matches_python_codec():
    payloads = [encode_event(e) for e in _events()]
    seam = codec.get_codec("json").decode(payloads,
                                          prefer_gil_release=True)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(seam, oracle)


@pytest.mark.parametrize("planar", [True, False])
def test_binary_codec_matches_legacy_decode(planar):
    rng = np.random.default_rng(1)
    roster = rng.integers(10_000, 50_000, 500).astype(np.uint32)
    cols = synth_columns(rng, 256, roster, num_lectures=8)
    frame = frame_from_columns(cols, planar=planar)
    seam = codec.get_codec("binary").decode([frame])
    legacy = decode_binary_batch(frame)
    _assert_cols_equal(seam, legacy)
    # Multi-frame decode concatenates in payload order.
    two = codec.get_codec("binary").decode([frame, frame])
    for k in COLS:
        assert np.array_equal(np.asarray(two[k]),
                              np.concatenate([np.asarray(legacy[k])] * 2))


def test_assemble_then_dispatch_decode_round_trips():
    """decode -> assemble -> decode_frame (the dispatcher's entry) is
    the identity for every codec."""
    events = _events(48, seed=2)
    json_payloads = [encode_event(e) for e in events]
    bin_frame = encode_binary_batch(events)
    for name, payloads in (("json", json_payloads),
                           ("binary", [bin_frame])):
        c = codec.get_codec(name)
        cols = c.decode(payloads)
        block = c.assemble(cols)
        _assert_cols_equal(codec.decode_frame(block), cols)
        hot = codec.decode_frame(block, include_truth=False)
        assert "is_valid" not in hot
        _assert_cols_equal(hot, cols,
                           keys=[k for k in COLS if k != "is_valid"])


def test_codec_sniffing_and_frame_event_count():
    events = _events(8, seed=3)
    json_payload = encode_event(events[0])
    bin_frame = encode_binary_batch(events)
    planar = encode_planar_batch(columns_from_events(events))
    assert codec.codec_for_frame(json_payload).name == "json"
    assert codec.codec_for_frame(bin_frame).name == "binary"
    assert codec.codec_for_frame(planar).name == "binary"
    assert codec.frame_event_count(bin_frame) == len(events)
    assert codec.frame_event_count(planar) == len(events)
    with pytest.raises(ValueError):
        codec.frame_event_count(json_payload)
    with pytest.raises(KeyError):
        codec.get_codec("carrier-pigeon")


def test_decode_frame_json_payload():
    e = _events(1, seed=4)[0]
    cols = codec.decode_frame(encode_event(e))
    oracle = columns_from_events([e])
    _assert_cols_equal(cols, oracle)


def test_merge_columns_concatenates():
    events = _events(10, seed=5)
    a = columns_from_events(events[:4])
    b = columns_from_events(events[4:])
    merged = codec.merge_columns([a, b])
    _assert_cols_equal(merged, columns_from_events(events))
    assert codec.merge_columns([a]) is a


# ---------------------------------------------------------------------------
# Vectorized batch scanner: differential vs the exact Python codec
# ---------------------------------------------------------------------------

FALLBACK_PAYLOADS = [
    # timezone suffix -> row fallback
    b'{"student_id": 7, "timestamp": "2026-07-14T08:30:00Z", '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # non-6-digit fraction
    b'{"student_id": 8, "timestamp": "2026-07-14T08:30:00.12", '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # non-digit lecture tail (murmur3 hashing path)
    b'{"student_id": 9, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "LECTURE_X", "is_valid": false, '
    b'"event_type": "entry"}',
    # non-LECTURE prefix
    b'{"student_id": 10, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "SEMINAR_99", "is_valid": false, '
    b'"event_type": "exit"}',
    # 9-digit already-hashed code round-trip (fast shape)
    b'{"student_id": 11, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "LECTURE_123456789", "is_valid": true, '
    b'"event_type": "exit"}',
    # reordered keys -> fallback
    b'{"timestamp": "2026-07-14T08:30:00", "student_id": 12, '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # compact separators (non-default json.dumps) -> fallback
    b'{"student_id":13,"timestamp":"2026-07-14T08:30:00",'
    b'"lecture_id":"LECTURE_20260714","is_valid":true,'
    b'"event_type":"entry"}',
]


def test_vector_scanner_differential_mixed_shapes():
    fast = [encode_event(e) for e in _events(40, seed=6)]
    frac = [encode_event(AttendanceEvent(
        5, "2026-01-02T23:59:59.123456", "LECTURE_20260102", False,
        "exit"))]
    payloads = fast[:10] + FALLBACK_PAYLOADS + fast[10:] + frac
    got = codec.scan_json_batch_columns(payloads)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(got, oracle)


def test_vector_scanner_empty_and_bounds():
    empty = codec.scan_json_batch_columns([])
    assert all(len(empty[k]) == 0 for k in COLS)
    # uint32 extremes and minimal ids
    payloads = [
        b'{"student_id": 0, "timestamp": "1970-01-01T00:00:00", '
        b'"lecture_id": "LECTURE_19700101", "is_valid": false, '
        b'"event_type": "entry"}',
        b'{"student_id": 4294967295, "timestamp": '
        b'"2099-12-31T23:59:59", "lecture_id": "LECTURE_20991231", '
        b'"is_valid": true, "event_type": "exit"}',
    ]
    got = codec.scan_json_batch_columns(payloads)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(got, oracle)


def test_vector_scanner_raises_on_malformed_json():
    with pytest.raises(Exception):
        codec.scan_json_batch_columns([b"not json at all"])
