"""Codec-seam tests (ISSUE 6): every wire round-trips through the
extracted decode -> assemble -> dispatch interface identically to the
pre-refactor paths, and the vectorized batch scanner is differentially
identical to the exact Python codec on every payload shape."""

import numpy as np
import pytest

from attendance_tpu.pipeline import codec
from attendance_tpu.pipeline.events import (
    AttendanceEvent, columns_from_events, decode_binary_batch,
    decode_event_batch, decode_json_batch_columns, encode_binary_batch,
    encode_event, encode_planar_batch)
from attendance_tpu.pipeline.loadgen import frame_from_columns, synth_columns

COLS = ("student_id", "lecture_day", "micros", "is_valid", "event_type")


def _events(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return [AttendanceEvent(
        student_id=int(rng.integers(1, 1 << 31)),
        timestamp=f"2026-07-{1 + int(rng.integers(0, 28)):02d}"
                  f"T{int(rng.integers(0, 24)):02d}"
                  f":{int(rng.integers(0, 60)):02d}"
                  f":{int(rng.integers(0, 60)):02d}",
        lecture_id=f"LECTURE_202607{1 + int(rng.integers(0, 28)):02d}",
        is_valid=bool(rng.random() < 0.9),
        event_type="exit" if rng.random() < 0.5 else "entry")
        for _ in range(n)]


def _assert_cols_equal(a, b, keys=COLS):
    for k in keys:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


# ---------------------------------------------------------------------------
# Round-trip identity vs the pre-refactor paths
# ---------------------------------------------------------------------------

def test_json_codec_matches_legacy_decode():
    payloads = [encode_event(e) for e in _events()]
    seam = codec.get_codec("json").decode(payloads)
    legacy = decode_json_batch_columns(payloads)
    _assert_cols_equal(seam, legacy)


def test_json_codec_vector_engine_matches_python_codec():
    payloads = [encode_event(e) for e in _events()]
    seam = codec.get_codec("json").decode(payloads,
                                          prefer_gil_release=True)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(seam, oracle)


@pytest.mark.parametrize("planar", [True, False])
def test_binary_codec_matches_legacy_decode(planar):
    rng = np.random.default_rng(1)
    roster = rng.integers(10_000, 50_000, 500).astype(np.uint32)
    cols = synth_columns(rng, 256, roster, num_lectures=8)
    frame = frame_from_columns(cols, planar=planar)
    seam = codec.get_codec("binary").decode([frame])
    legacy = decode_binary_batch(frame)
    _assert_cols_equal(seam, legacy)
    # Multi-frame decode concatenates in payload order.
    two = codec.get_codec("binary").decode([frame, frame])
    for k in COLS:
        assert np.array_equal(np.asarray(two[k]),
                              np.concatenate([np.asarray(legacy[k])] * 2))


def test_assemble_then_dispatch_decode_round_trips():
    """decode -> assemble -> decode_frame (the dispatcher's entry) is
    the identity for every codec."""
    events = _events(48, seed=2)
    json_payloads = [encode_event(e) for e in events]
    bin_frame = encode_binary_batch(events)
    for name, payloads in (("json", json_payloads),
                           ("binary", [bin_frame])):
        c = codec.get_codec(name)
        cols = c.decode(payloads)
        block = c.assemble(cols)
        _assert_cols_equal(codec.decode_frame(block), cols)
        hot = codec.decode_frame(block, include_truth=False)
        assert "is_valid" not in hot
        _assert_cols_equal(hot, cols,
                           keys=[k for k in COLS if k != "is_valid"])


def test_codec_sniffing_and_frame_event_count():
    events = _events(8, seed=3)
    json_payload = encode_event(events[0])
    bin_frame = encode_binary_batch(events)
    planar = encode_planar_batch(columns_from_events(events))
    assert codec.codec_for_frame(json_payload).name == "json"
    assert codec.codec_for_frame(bin_frame).name == "binary"
    assert codec.codec_for_frame(planar).name == "binary"
    assert codec.frame_event_count(bin_frame) == len(events)
    assert codec.frame_event_count(planar) == len(events)
    with pytest.raises(ValueError):
        codec.frame_event_count(json_payload)
    with pytest.raises(KeyError):
        codec.get_codec("carrier-pigeon")


def test_decode_frame_json_payload():
    e = _events(1, seed=4)[0]
    cols = codec.decode_frame(encode_event(e))
    oracle = columns_from_events([e])
    _assert_cols_equal(cols, oracle)


def test_merge_columns_concatenates():
    events = _events(10, seed=5)
    a = columns_from_events(events[:4])
    b = columns_from_events(events[4:])
    merged = codec.merge_columns([a, b])
    _assert_cols_equal(merged, columns_from_events(events))
    assert codec.merge_columns([a]) is a


# ---------------------------------------------------------------------------
# Vectorized batch scanner: differential vs the exact Python codec
# ---------------------------------------------------------------------------

FALLBACK_PAYLOADS = [
    # timezone suffix -> row fallback
    b'{"student_id": 7, "timestamp": "2026-07-14T08:30:00Z", '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # non-6-digit fraction
    b'{"student_id": 8, "timestamp": "2026-07-14T08:30:00.12", '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # non-digit lecture tail (murmur3 hashing path)
    b'{"student_id": 9, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "LECTURE_X", "is_valid": false, '
    b'"event_type": "entry"}',
    # non-LECTURE prefix
    b'{"student_id": 10, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "SEMINAR_99", "is_valid": false, '
    b'"event_type": "exit"}',
    # 9-digit already-hashed code round-trip (fast shape)
    b'{"student_id": 11, "timestamp": "2026-07-14T08:30:00", '
    b'"lecture_id": "LECTURE_123456789", "is_valid": true, '
    b'"event_type": "exit"}',
    # reordered keys -> fallback
    b'{"timestamp": "2026-07-14T08:30:00", "student_id": 12, '
    b'"lecture_id": "LECTURE_20260714", "is_valid": true, '
    b'"event_type": "entry"}',
    # compact separators (non-default json.dumps) -> fallback
    b'{"student_id":13,"timestamp":"2026-07-14T08:30:00",'
    b'"lecture_id":"LECTURE_20260714","is_valid":true,'
    b'"event_type":"entry"}',
]


def test_vector_scanner_differential_mixed_shapes():
    fast = [encode_event(e) for e in _events(40, seed=6)]
    frac = [encode_event(AttendanceEvent(
        5, "2026-01-02T23:59:59.123456", "LECTURE_20260102", False,
        "exit"))]
    payloads = fast[:10] + FALLBACK_PAYLOADS + fast[10:] + frac
    got = codec.scan_json_batch_columns(payloads)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(got, oracle)


def test_vector_scanner_empty_and_bounds():
    empty = codec.scan_json_batch_columns([])
    assert all(len(empty[k]) == 0 for k in COLS)
    # uint32 extremes and minimal ids
    payloads = [
        b'{"student_id": 0, "timestamp": "1970-01-01T00:00:00", '
        b'"lecture_id": "LECTURE_19700101", "is_valid": false, '
        b'"event_type": "entry"}',
        b'{"student_id": 4294967295, "timestamp": '
        b'"2099-12-31T23:59:59", "lecture_id": "LECTURE_20991231", '
        b'"is_valid": true, "event_type": "exit"}',
    ]
    got = codec.scan_json_batch_columns(payloads)
    oracle = columns_from_events(decode_event_batch(payloads))
    _assert_cols_equal(got, oracle)


def test_vector_scanner_raises_on_malformed_json():
    with pytest.raises(Exception):
        codec.scan_json_batch_columns([b"not json at all"])


# ---------------------------------------------------------------------------
# COLW columnar wire (ISSUE 11): differential identity vs the JSON and
# binary oracles, fallback/out-of-range rows, loud corruption failure
# ---------------------------------------------------------------------------

def _colw_cols(n=512, seed=3, arrival=True):
    rng = np.random.default_rng(seed)
    if arrival:
        micros = (1_753_000_000_000_000
                  + np.cumsum(rng.integers(1, 2_000, n))).astype(
                      np.int64)
    else:
        micros = (1_753_000_000_000_000
                  + rng.integers(0, 86_400_000_000, n)).astype(np.int64)
    return {
        "student_id": rng.integers(10_000, 410_000, n,
                                   dtype=np.uint32),
        "lecture_day": (20_260_701
                        + rng.integers(0, 8, n)).astype(np.uint32),
        "micros": micros,
        "is_valid": rng.random(n) < 0.9,
        "event_type": (rng.random(n) < 0.5).astype(np.int8),
    }


def _events_from_cols(cols):
    """The same logical events as reference-wire JSON payloads (the
    oracle the differential tests compare against)."""
    from datetime import datetime, timezone
    out = []
    for i in range(len(cols["student_id"])):
        ts = datetime.fromtimestamp(
            int(cols["micros"][i]) / 1e6,
            tz=timezone.utc).replace(tzinfo=None)
        out.append(AttendanceEvent(
            int(cols["student_id"][i]),
            ts.isoformat(),
            f"LECTURE_{int(cols['lecture_day'][i])}",
            bool(cols["is_valid"][i]),
            "exit" if cols["event_type"][i] else "entry"))
    return out


@pytest.mark.parametrize("arrival", [True, False])
def test_colw_differential_vs_json_and_binary_oracles(arrival):
    cols = _colw_cols(arrival=arrival)
    colw = codec.encode_columnar_batch(cols)
    got = codec.decode_columnar_frame(colw)
    # vs the binary (planar) oracle
    planar = encode_planar_batch(cols)
    _assert_cols_equal(got, decode_binary_batch(planar))
    # vs the JSON oracle over the same logical events
    payloads = [encode_event(e) for e in _events_from_cols(cols)]
    _assert_cols_equal(got, decode_json_batch_columns(payloads))
    # and the codec-seam entry points route it identically
    _assert_cols_equal(got, codec.decode_frame(colw))
    _assert_cols_equal(got,
                       codec.get_codec("columnar").decode([colw]))


def test_colw_out_of_range_timestamps_roundtrip():
    """Deltas past every narrow width (negative epochs, +/-2^62
    micros, out-of-order rows) fall back to the 8-byte width and
    round-trip exactly."""
    cols = _colw_cols(64)
    m = cols["micros"].copy()
    m[1] = -(2 ** 62)
    m[2] = 2 ** 62
    m[3] = 0
    cols["micros"] = m
    out = codec.decode_columnar_frame(codec.encode_columnar_batch(cols))
    assert np.array_equal(out["micros"], m)


def test_colw_both_id_modes_exercised_and_identical():
    n = 512
    cols = _colw_cols(n)
    # lecture_day: 8 uniques over 512 rows -> dictionary wins;
    # student_id: ~unique over a wide range -> width-packing wins.
    body = codec.encode_columnar_batch(cols, checksum=False)
    # one of each mode byte must appear (sanity that the test really
    # covers both encoders)
    got = codec.decode_columnar_frame(body)
    _assert_cols_equal(got, cols)
    # force dictionary on students too (tiny roster, repeated ids)
    rng = np.random.default_rng(0)
    cols2 = dict(cols, student_id=rng.choice(
        np.array([7, 9, 11], np.uint32), n))
    got2 = codec.decode_columnar_frame(
        codec.encode_columnar_batch(cols2))
    _assert_cols_equal(got2, cols2)


def test_colw_empty_and_single_row():
    for n in (0, 1):
        cols = {k: v[:n] for k, v in _colw_cols(8).items()}
        out = codec.decode_columnar_frame(
            codec.encode_columnar_batch(cols))
        _assert_cols_equal(out, cols)


def test_colw_corruption_rejected_loudly():
    """A flipped byte anywhere in a checksummed COLW frame raises at
    decode (FrameChecksumError is a ValueError) — the poison path's
    trigger; silent event mutation is impossible by construction."""
    from attendance_tpu.transport.framing import FrameChecksumError
    colw = bytearray(codec.encode_columnar_batch(_colw_cols(128)))
    for pos in (5, 40, len(colw) // 2, len(colw) - 3):
        bad = bytearray(colw)
        bad[pos] ^= 0x40
        with pytest.raises((FrameChecksumError, ValueError)):
            codec.decode_columnar_frame(bytes(bad))


def test_colw_dictionary_miss_fails_loudly():
    """A dictionary index past the dictionary (hand-corrupted BARE
    body, so no checksum catches it first) must raise, never guess a
    value."""
    n = 64
    rng = np.random.default_rng(1)
    # Wide values at tiny cardinality: dictionary mode wins (packing
    # would need 3 bytes/row; the dict costs one index byte).
    cols = dict(_colw_cols(n),
                student_id=rng.choice(
                    np.array([100_000, 200_000], np.uint32), n))
    body = bytearray(codec.encode_columnar_batch(cols, checksum=False))
    # find the student dict column: mode byte 0x01 after the ts block.
    # ts block: magic(4) + n(4) + base(8) + w(1) + deltas
    ts_w = body[16]
    off = 17 + (n - 1) * ts_w
    assert body[off] == 1, "expected dictionary mode for students"
    k = int.from_bytes(body[off + 1:off + 5], "little")
    iw = body[off + 5 + 4 * k]
    idx0 = off + 5 + 4 * k + 1
    body[idx0] = 0xFF  # index 255 >> k
    with pytest.raises(ValueError, match="dictionary index"):
        codec.decode_columnar_frame(bytes(body))


def test_colw_truncation_fails_loudly():
    body = codec.encode_columnar_batch(_colw_cols(128), checksum=False)
    for cut in (6, 20, len(body) // 2, len(body) - 1):
        with pytest.raises(ValueError):
            codec.decode_columnar_frame(body[:cut])
    with pytest.raises(ValueError, match="trailing"):
        codec.decode_columnar_frame(body + b"\x00")


def test_colw_frame_event_count_and_sniff():
    cols = _colw_cols(200)
    wrapped = codec.encode_columnar_batch(cols)
    bare = codec.encode_columnar_batch(cols, checksum=False)
    for f in (wrapped, bare, memoryview(wrapped)):
        assert codec.frame_event_count(f) == 200
        assert codec.codec_for_frame(f).name == "columnar"
    assert codec.columnar_wire_bytes_per_event([wrapped]) == \
        pytest.approx(len(wrapped) / 200)


def test_colw_multi_payload_decode_merges():
    a, b = _colw_cols(64, seed=1), _colw_cols(32, seed=2)
    got = codec.get_codec("columnar").decode(
        [codec.encode_columnar_batch(a), codec.encode_columnar_batch(b)])
    want = codec.merge_columns([a, b])
    _assert_cols_equal(got, want)


def test_colw_hostile_event_count_rejected_before_allocation():
    """A corrupt bare header claiming 2^32-1 events must raise at the
    bounds check, never attempt the multi-GB column allocation (the
    unchecksummed legacy-tolerance path is exactly where a mangled
    count can reach the decoder)."""
    import struct
    hostile = codec.COLW_MAGIC + struct.pack("<I", 0xFFFFFFFF) \
        + b"\x00" * 16
    with pytest.raises(ValueError, match="impossible"):
        codec.decode_columnar_frame(hostile)
