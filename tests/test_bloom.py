"""Bloom filter property tests: sizing, no false negatives, FPR budget.

Mirrors the accuracy contract of the reference's BF.RESERVE with
error_rate=0.01, capacity=100000 (reference attendance_processor.py:83-88).
"""

import numpy as np
import pytest

from attendance_tpu.models.bloom import (
    BloomFilter, derive_bloom_params)


def test_sizing_matches_standard_math():
    p = derive_bloom_params(100_000, 0.01)
    # -ln(0.01)/ln(2)^2 = 9.585 bits/key -> k = ceil(0.693*9.585) = 7
    assert p.k == 7
    assert 9.0 * 100_000 <= p.m_bits <= 10.5 * 100_000
    assert p.m_bits % 512 == 0


@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_no_false_negatives(layout):
    bf = BloomFilter(capacity=20_000, error_rate=0.01, layout=layout)
    keys = np.arange(10_000, 30_000, dtype=np.uint32)
    bf.add(keys)
    assert bf.contains(keys).all()


@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_fpr_within_budget(layout):
    cap = 50_000
    bf = BloomFilter(capacity=cap, error_rate=0.01, layout=layout)
    members = np.arange(cap, dtype=np.uint32)
    bf.add(members)
    non_members = np.arange(1 << 20, (1 << 20) + 200_000, dtype=np.uint32)
    fp = bf.contains(non_members).mean()
    # At exactly full capacity the design point is eps=0.01; allow modest
    # statistical slack on 200k probes.
    assert fp <= 0.013, fp
    assert bf.estimated_fpr() <= 0.013


def test_masked_add_ignores_padding():
    bf = BloomFilter(capacity=1_000, error_rate=0.01)
    keys = np.array([1, 2, 3, 4], dtype=np.uint32)
    mask = np.array([True, True, False, False])
    bf.add(keys, mask=mask)
    got = bf.contains(keys)
    assert got[0] and got[1]
    # Masked-out keys were not inserted (could still be FPs, but with a
    # near-empty 9.6k-bit filter the chance is ~(8/9600)^7 ~ 0).
    assert not got[2] and not got[3]


def test_duplicate_and_replayed_batches_are_idempotent():
    bf = BloomFilter(capacity=1_000, error_rate=0.01)
    keys = np.array([7, 7, 7, 42], dtype=np.uint32)
    bf.add(keys)
    before = np.asarray(bf.bits).sum()
    bf.add(keys)  # replay
    after = np.asarray(bf.bits).sum()
    assert before == after
    assert bf.contains(np.array([7, 42], dtype=np.uint32)).all()


# ---------------------------------------------------------------------------
# Bit-packed representation (uint32 words, 1/8th the HBM)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_packed_bit_identical_to_byte_path(layout):
    """Packed add/contains answer bit-identically to the byte-per-bit
    path on the same key stream (same bloom_positions underneath)."""
    import jax.numpy as jnp
    from attendance_tpu.models.bloom import (
        bloom_add, bloom_add_packed, bloom_contains, bloom_contains_words,
        bloom_init, bloom_packed_init, pack_bloom_bits, unpack_bloom_bits)

    rng = np.random.default_rng(11)
    params = derive_bloom_params(20_000, 0.01, layout)
    roster = rng.choice(1 << 31, size=10_000, replace=False
                        ).astype(np.uint32)
    bits = bloom_add(bloom_init(params), jnp.asarray(roster), params)
    words = bloom_add_packed(bloom_packed_init(params),
                             jnp.asarray(roster), params)
    np.testing.assert_array_equal(
        np.asarray(pack_bloom_bits(bits)), np.asarray(words))
    np.testing.assert_array_equal(
        np.asarray(unpack_bloom_bits(words)), np.asarray(bits))

    probe = np.concatenate([
        roster[:2_000],
        rng.integers(1 << 31, 1 << 32, 8_000).astype(np.uint32)])
    byte_ans = np.asarray(bloom_contains(bits, jnp.asarray(probe), params))
    word_ans = np.asarray(
        bloom_contains_words(words, jnp.asarray(probe), params))
    np.testing.assert_array_equal(byte_ans, word_ans)
    assert word_ans[:2_000].all()  # no false negatives

    # Masked incremental adds stay identical too.
    keys2 = rng.integers(0, 1 << 32, 2_048, dtype=np.uint32)
    mask = rng.random(2_048) < 0.6
    bits2 = bloom_add(bits, jnp.asarray(keys2), params, jnp.asarray(mask))
    words2 = bloom_add_packed(words, jnp.asarray(keys2), params,
                              jnp.asarray(mask))
    np.testing.assert_array_equal(
        np.asarray(pack_bloom_bits(bits2)), np.asarray(words2))


def test_packed_memory_is_one_eighth():
    from attendance_tpu.models.bloom import (
        bloom_init, bloom_packed_init)
    params = derive_bloom_params(100_000, 0.01, "blocked")
    assert bloom_packed_init(params).nbytes * 8 == bloom_init(params).nbytes


def test_packed_replay_is_idempotent():
    import jax.numpy as jnp
    from attendance_tpu.models.bloom import (
        bloom_add_packed, bloom_packed_init)
    params = derive_bloom_params(1_000, 0.01, "blocked")
    keys = jnp.asarray(np.array([7, 7, 7, 42], dtype=np.uint32))
    words = bloom_add_packed(bloom_packed_init(params), keys, params)
    again = bloom_add_packed(words, keys, params)
    np.testing.assert_array_equal(np.asarray(words), np.asarray(again))
