"""Federation plane (attendance_tpu/federation): CRDT merge-core
property tests (commutativity / associativity / idempotence of Bloom-OR
and HLL register-max on the numpy AND device paths), merge-of-deltas ==
merge-of-full-states, random K-way interleavings converging to the
single-process oracle, the versioned merge-frame wire, the shard map,
fence-gossip end to end over in-process pipelines, dead-peer chain
recovery, and the doctor's merge-lag rows.
"""

import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.federation.frames import (
    FRAME_VERSION, MergeFrame, decode_frame, encode_frame)
from attendance_tpu.federation.gossip import Aggregator, FenceGossip
from attendance_tpu.federation.merge import GeometryMismatch, MergedView
from attendance_tpu.federation.shard import (
    ShardMap, shard_of_keys, shard_topic)
from attendance_tpu.models.bloom import bloom_or_words, bloom_or_words_np
from attendance_tpu.models.hll import hll_merge, hll_merge_np
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import (
    frame_from_columns, generate_frames, synth_columns)
from attendance_tpu.serve.engine import QueryEngine
from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient)

M = 1 << 8  # small register width for property tests (not the real 2^14)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.disable()
    yield
    obs.disable()


# -- CRDT property tests -----------------------------------------------------

def _rand_words(rng, n=64):
    return rng.integers(0, 1 << 32, n, dtype=np.uint32)


def _rand_regs(rng, banks, m=M):
    # Realistic HLL register range is [0, ~50]; uint8 keeps max exact.
    return rng.integers(0, 51, (banks, m), dtype=np.uint8)


def test_bloom_or_np_properties():
    rng = np.random.default_rng(0)
    a, b, c = (_rand_words(rng) for _ in range(3))
    assert (bloom_or_words_np(a, b) == bloom_or_words_np(b, a)).all()
    assert (bloom_or_words_np(bloom_or_words_np(a, b), c)
            == bloom_or_words_np(a, bloom_or_words_np(b, c))).all()
    assert (bloom_or_words_np(a, a) == a).all()
    # Merging in a filter's own state is a no-op (idempotence under
    # replay — the failover safety property).
    ab = bloom_or_words_np(a, b)
    assert (bloom_or_words_np(ab, b) == ab).all()


def test_bloom_or_np_geometry_mismatch():
    rng = np.random.default_rng(1)
    with pytest.raises(ValueError):
        bloom_or_words_np(_rand_words(rng, 64), _rand_words(rng, 32))


def test_bloom_or_device_matches_np():
    rng = np.random.default_rng(2)
    a, b = _rand_words(rng), _rand_words(rng)
    dev = np.asarray(bloom_or_words(jnp.asarray(a), jnp.asarray(b)))
    assert (dev == bloom_or_words_np(a, b)).all()


def test_hll_merge_np_properties():
    rng = np.random.default_rng(3)
    a, b, c = (_rand_regs(rng, 4) for _ in range(3))
    assert (hll_merge_np(a, b) == hll_merge_np(b, a)).all()
    assert (hll_merge_np(hll_merge_np(a, b), c)
            == hll_merge_np(a, hll_merge_np(b, c))).all()
    assert (hll_merge_np(a, a) == a).all()


def test_hll_merge_np_bank_growth():
    # Replicas that grew their bank arrays at different times merge
    # with the shorter stack zero-extended (0 is max's identity).
    rng = np.random.default_rng(4)
    a, b = _rand_regs(rng, 2), _rand_regs(rng, 5)
    out = hll_merge_np(a, b)
    assert out.shape == (5, M)
    assert (out[:2] == np.maximum(a, b[:2])).all()
    assert (out[2:] == b[2:]).all()
    assert (hll_merge_np(a, b) == hll_merge_np(b, a)).all()


def test_hll_merge_np_width_mismatch():
    rng = np.random.default_rng(5)
    with pytest.raises(ValueError):
        hll_merge_np(_rand_regs(rng, 2, 64), _rand_regs(rng, 2, 128))


def test_hll_merge_device_matches_np():
    rng = np.random.default_rng(6)
    a, b = _rand_regs(rng, 4), _rand_regs(rng, 4)
    dev = np.asarray(hll_merge(jnp.asarray(a), jnp.asarray(b)))
    assert (dev == hll_merge_np(a, b)).all()


# -- merge-frame wire --------------------------------------------------------

def _mk_frame_bytes(worker="w0", kind="delta", seq=0, incarnation=1.0,
                    events=100, bank_of=None, arrays=None, **kw):
    bank_of = {20260701: 0, 20260702: 1} if bank_of is None else bank_of
    if arrays is None and kind == "delta":
        arrays = {"bank_idx": np.array([0, 1], np.int32),
                  "rows": np.zeros((2, 1 << 14), np.uint8),
                  "counts": np.zeros((2, 2), np.uint32)}
    return encode_frame(
        worker=worker, kind=kind, incarnation=incarnation, seq=seq,
        shard=0, fence_ts=time.time(), events=events, bank_of=bank_of,
        m_bits=1 << 10, k=7, precision=14, arrays=arrays, **kw)


def test_frame_roundtrip():
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 51, (3, 1 << 14), dtype=np.uint8)
    payload = _mk_frame_bytes(
        arrays={"bank_idx": np.array([4, 0, 2], np.int32),
                "rows": rows,
                "counts": np.array([[9, 0], [1, 0]], np.uint32)},
        bank_of={20260701: 4, 20260702: 0, 20260703: 2})
    frame = decode_frame(payload)
    assert frame.worker == "w0" and frame.kind == "delta"
    assert frame.bank_of == {20260701: 4, 20260702: 0, 20260703: 2}
    assert (frame.arrays["rows"] == rows).all()
    assert frame.arrays["bank_idx"].dtype == np.int32
    assert frame.events == 100 and frame.m_bits == 1 << 10


def test_frame_version_gate():
    payload = bytearray(_mk_frame_bytes(kind="heartbeat", arrays={}))
    payload[0:2] = (FRAME_VERSION + 1).to_bytes(2, "little")
    with pytest.raises(ValueError, match="version"):
        decode_frame(bytes(payload))


def test_frame_unknown_kind_rejected():
    with pytest.raises(ValueError):
        encode_frame(worker="w0", kind="gossip?", incarnation=1.0,
                     seq=0, shard=0, fence_ts=0.0, events=0)


# -- shard map ---------------------------------------------------------------

def test_shard_of_keys_partitions():
    keys = np.arange(10_000, 60_000, dtype=np.uint32)
    shards = shard_of_keys(keys, 4)
    assert shards.min() >= 0 and shards.max() < 4
    # Balanced within a generous tolerance (hash partition).
    counts = np.bincount(shards, minlength=4)
    assert counts.min() > len(keys) // 8
    # Deterministic and independent of array order.
    assert (shard_of_keys(keys[::-1], 4)[::-1] == shards).all()
    assert shard_topic("events", 2) == "events.s2"


def test_shard_map_versioning():
    m = ShardMap(3)
    assert m.version == 1
    assert m.claim(0, "w0") and m.claim(1, "w1") and m.claim(2, "w2")
    assert m.version == 1  # startup claims are not reassignments
    assert not m.claim(0, "w0")  # idempotent re-claim
    moved = m.reassign("w1", None)
    assert moved == [1] and m.version == 2 and m.owner_of(1) is None
    # A takeover claiming the ORPHANED shard is a fresh claim (the
    # reassignment already bumped); claiming over a LIVE owner bumps.
    assert m.claim(1, "w9") and m.version == 2
    assert m.shards_of("w9") == [1]
    assert m.claim(2, "w9") and m.version == 3
    with pytest.raises(ValueError):
        m.claim(5, "w0")


# -- merge core: deltas vs fulls, interleavings, staleness -------------------

def _worker_stream(rng, worker, shard, days, n_frames=6, p=14):
    """A plausible fence stream: one full frame then deltas, with
    monotone counters and per-worker day->bank assignment in arrival
    order."""
    m = 1 << p
    bank_of, regs = {}, np.zeros((0, m), np.uint8)
    bloom = rng.integers(0, 1 << 32, 128, dtype=np.uint32)
    frames, events = [], 0
    for seq in range(n_frames):
        # Touch a random subset of days; maybe discover a new one. A
        # newly discovered bank is always dirty (a day only registers
        # because events landed in it), exactly like the pipeline's
        # dirty-day capture — so every bank is named by some delta.
        new_banks = []
        for day in rng.choice(days, rng.integers(1, len(days) + 1),
                              replace=False):
            if int(day) not in bank_of:
                bank_of[int(day)] = len(bank_of)
                new_banks.append(len(bank_of) - 1)
                regs = np.vstack([regs, np.zeros((1, m), np.uint8)])
        touched = rng.choice(list(bank_of.values()),
                             rng.integers(1, len(bank_of) + 1),
                             replace=False)
        touched = np.unique(np.concatenate(
            [touched, np.asarray(new_banks, touched.dtype)])
        ).astype(np.int64)
        bump = np.zeros_like(regs)
        idx = (rng.integers(0, m, 64), )
        for b in touched:
            bump[b][idx] = rng.integers(1, 51, 64)
        regs = np.maximum(regs, bump)
        events += int(rng.integers(100, 1000))
        counts = np.zeros((2, 2), np.uint32)
        counts[0, 0] = events
        common = dict(worker=worker, incarnation=1.0, seq=seq,
                      shard=shard, fence_ts=time.time(), events=events,
                      bank_of=dict(bank_of), m_bits=1 << 12, k=5,
                      precision=p, num_banks=regs.shape[0])
        if seq == 0:
            frames.append(encode_frame(kind="full", arrays={
                "bloom": bloom, "regs": regs.copy(),
                "counts": counts}, **common))
        else:
            frames.append(encode_frame(kind="delta", arrays={
                "bank_idx": touched.astype(np.int32),
                "rows": regs[touched].copy(),
                "counts": counts}, **common))
    final = dict(bank_of=bank_of, regs=regs, bloom=bloom,
                 events=events)
    return frames, final


def _fold_all(payloads, p=14):
    view = MergedView(p)
    for payload in payloads:
        view.fold(decode_frame(payload))
    return view


def _oracle(finals):
    regs_by_day, bloom = {}, None
    for f in finals:
        inv = {b: d for d, b in f["bank_of"].items()}
        for b, d in inv.items():
            row = f["regs"][b]
            regs_by_day[d] = (np.maximum(regs_by_day[d], row)
                              if d in regs_by_day else row.copy())
        bloom = f["bloom"] if bloom is None \
            else bloom_or_words_np(bloom, f["bloom"])
    return regs_by_day, bloom


def test_merge_of_deltas_equals_merge_of_fulls():
    rng = np.random.default_rng(8)
    days = [20260701 + i for i in range(5)]
    frames, final = _worker_stream(rng, "w0", 0, days)
    by_deltas = _fold_all(frames)
    # One full frame carrying the worker's end state.
    counts = np.zeros((2, 2), np.uint32)
    counts[0, 0] = final["events"]
    full = encode_frame(
        worker="w0", kind="full", incarnation=1.0, seq=99, shard=0,
        fence_ts=time.time(), events=final["events"],
        bank_of=final["bank_of"], m_bits=1 << 12, k=5, precision=14,
        arrays={"bloom": final["bloom"], "regs": final["regs"],
                "counts": counts})
    by_full = _fold_all([full])
    assert by_deltas.events == by_full.events == final["events"]
    a, b = by_deltas.regs_by_day(), by_full.regs_by_day()
    assert set(a) == set(b)
    for day in a:
        assert (a[day] == b[day]).all(), day
    assert (by_deltas.bloom_words == by_full.bloom_words).all()


@pytest.mark.parametrize("trial", range(3))
def test_kway_interleavings_converge_to_oracle(trial):
    rng = np.random.default_rng(100 + trial)
    days = [20260701 + i for i in range(4)]
    streams, finals = [], []
    for w in range(3):
        frames, final = _worker_stream(rng, f"w{w}", w, days,
                                       n_frames=5)
        streams.append(frames)
        finals.append(final)
    oracle_regs, oracle_bloom = _oracle(finals)
    # Random global interleaving preserving NOTHING (not even
    # per-worker order), plus a duplicated random subset: OR/max make
    # both harmless; only counters need the (incarnation, seq) fold.
    merged = [f for s in streams for f in s]
    order = rng.permutation(len(merged))
    payloads = [merged[i] for i in order]
    dup = [merged[i] for i in
           rng.choice(len(merged), 4, replace=False)]
    view = _fold_all(payloads + dup)
    assert view.events == sum(f["events"] for f in finals)
    got = view.regs_by_day()
    assert set(got) == set(oracle_regs)
    for day in got:
        assert (got[day] == oracle_regs[day]).all(), day
    assert (view.bloom_words == oracle_bloom).all()


def test_stale_incarnation_counters_ignored_sketch_folded():
    view = MergedView(14)
    m = 1 << 14
    regs2 = np.zeros((1, m), np.uint8)
    regs2[0, 7] = 9
    counts = np.zeros((2, 2), np.uint32)
    view.fold(MergeFrame(
        dict(worker="w0", kind="full", incarnation=2.0, seq=0, shard=0,
             fence_ts=time.time(), events=500, roster_size=10,
             m_bits=64, k=3, precision=14, bank_of={20260701: 0}),
        dict(bloom=np.array([1, 0], np.uint32), regs=regs2,
             counts=counts)))
    # A LATE frame from the dead incarnation 1.0: more events claimed,
    # a register the takeover never saw.
    regs1 = np.zeros((1, m), np.uint8)
    regs1[0, 3] = 21
    info = view.fold(MergeFrame(
        dict(worker="w0", kind="full", incarnation=1.0, seq=9, shard=0,
             fence_ts=time.time(), events=9_999, roster_size=10,
             m_bits=64, k=3, precision=14, bank_of={20260701: 0}),
        dict(bloom=np.array([0, 2], np.uint32), regs=regs1,
             counts=counts)))
    assert info["stale"]
    assert view.stale_frames == 1
    assert view.events == 500  # stale counters never fold
    row = view.regs_by_day()[20260701]
    assert row[7] == 9 and row[3] == 21  # sketch state still folded
    assert (view.bloom_words == np.array([1, 2], np.uint32)).all()


def test_stale_frame_does_not_refresh_liveness():
    """A superseded zombie's heartbeats must not keep the worker-id
    ledger fresh: the takeover successor (same id, higher incarnation)
    owns liveness, or its own death could never be detected."""
    view = MergedView(14)
    hdr = dict(worker="w0", kind="heartbeat", shard=0,
               fence_ts=0.0, events=1, roster_size=1,
               m_bits=0, k=0, precision=14, bank_of={})
    view.fold(MergeFrame(dict(hdr, incarnation=2.0, seq=0), {}),
              now=100.0)
    assert view.workers["w0"].last_seen == 100.0
    # Zombie old-incarnation heartbeat much later: stale, no refresh.
    info = view.fold(MergeFrame(dict(hdr, incarnation=1.0, seq=9), {}),
                     now=500.0)
    assert info["stale"]
    assert view.workers["w0"].last_seen == 100.0
    # Current-incarnation traffic still refreshes.
    view.fold(MergeFrame(dict(hdr, incarnation=2.0, seq=1), {}),
              now=600.0)
    assert view.workers["w0"].last_seen == 600.0


def test_claim_incarnation_monotonic_across_takeovers(tmp_path):
    """Successive claims on one chain dir strictly increase even when
    the claimant's wall clock trails the previous owner's (the
    cross-host takeover case)."""
    from attendance_tpu.federation.gossip import claim_incarnation

    d = str(tmp_path / "chain")
    inc1 = claim_incarnation(d)
    inc2 = claim_incarnation(d)
    assert inc2 > inc1
    # Previous owner minted on a clock far ahead of ours: the durable
    # high-water mark still wins over time.time().
    (tmp_path / "chain" / "INCARNATION").write_text("9e9")
    assert claim_incarnation(d) > 9e9
    # No chain dir configured: plain wall clock.
    assert claim_incarnation("") > 0


def test_geometry_mismatch_fails_loudly():
    view = MergedView(14)
    counts = np.zeros((2, 2), np.uint32)
    view.fold(MergeFrame(
        dict(worker="w0", kind="full", incarnation=1.0, seq=0, shard=0,
             fence_ts=0.0, events=0, roster_size=0, m_bits=256, k=3,
             precision=14, bank_of={}),
        dict(bloom=np.zeros(8, np.uint32),
             regs=np.zeros((1, 1 << 14), np.uint8), counts=counts)))
    with pytest.raises(GeometryMismatch):
        view.fold(MergeFrame(
            dict(worker="w1", kind="full", incarnation=1.0, seq=0,
                 shard=1, fence_ts=0.0, events=0, roster_size=0,
                 m_bits=512, k=3, precision=14, bank_of={}),
            dict(bloom=np.zeros(16, np.uint32),
                 regs=np.zeros((1, 1 << 14), np.uint8),
                 counts=counts)))
    with pytest.raises(GeometryMismatch):
        view.fold(MergeFrame(
            dict(worker="w2", kind="full", incarnation=1.0, seq=0,
                 shard=1, fence_ts=0.0, events=0, roster_size=0,
                 m_bits=256, k=3, precision=12, bank_of={}), {}))
    # Same m_bits, different probe count: the reader would probe k
    # positions the writer never set — false negatives, so reject.
    with pytest.raises(GeometryMismatch):
        view.fold(MergeFrame(
            dict(worker="w3", kind="full", incarnation=1.0, seq=0,
                 shard=1, fence_ts=0.0, events=0, roster_size=0,
                 m_bits=256, k=5, precision=14, bank_of={}),
            dict(bloom=np.zeros(8, np.uint32),
                 regs=np.zeros((1, 1 << 14), np.uint8),
                 counts=counts)))


def test_aggregator_rejects_geometry_loudly_and_keeps_serving():
    """A misconfigured peer's frames are dropped with attribution (the
    geometry_rejects counter doctor fails on), never folded, and never
    allowed to kill the aggregator's poll loop."""
    from attendance_tpu.transport.memory_broker import MemoryBroker
    broker = MemoryBroker()
    agg = Aggregator(client=MemoryClient(broker), topic="geo-gossip",
                     num_shards=2, dead_after_s=1e9, precision=14)
    producer = MemoryClient(broker).create_producer("geo-gossip")
    counts = np.zeros((2, 2), np.uint32)
    good = encode_frame(
        worker="w0", kind="full", incarnation=1.0, seq=0, shard=0,
        fence_ts=time.time(), events=10, m_bits=256, k=3, precision=14,
        bank_of={}, arrays=dict(bloom=np.zeros(8, np.uint32),
                                regs=np.zeros((1, 1 << 14), np.uint8),
                                counts=counts))
    bad = encode_frame(
        worker="w1", kind="full", incarnation=1.0, seq=0, shard=1,
        fence_ts=time.time(), events=7, m_bits=256, k=5, precision=14,
        bank_of={}, arrays=dict(bloom=np.zeros(8, np.uint32),
                                regs=np.zeros((1, 1 << 14), np.uint8),
                                counts=counts))
    producer.send(good)
    producer.send(bad)
    producer.send(good)  # the good peer keeps folding after the reject
    try:
        folded = _drain(agg, min_folds=2)
        assert folded == 2
        assert agg.geometry_rejects == 1
        stats = agg.stats()
        assert stats["geometry_rejects"] == 1
        assert "w1" not in stats["workers"] or \
            stats["workers"]["w1"]["events"] == 0
        assert stats["events"] == 10  # the bad peer's counters never fold
    finally:
        agg.stop()


# -- fence gossip end to end (in-process pipelines) --------------------------

def _federated_pipes(broker, tmp, K, roster, num_banks=8,
                     snapshot_every=2):
    pipes = []
    for s in range(K):
        cfg = Config(
            bloom_filter_capacity=20_000, transport_backend="memory",
            pulsar_topic=f"events.s{s}",
            snapshot_dir=str(tmp / f"w{s}"),
            snapshot_every_batches=snapshot_every,
            fed_worker=f"w{s}", fed_shard=s, fed_shards=K,
            fed_gossip_topic="fed-gossip",
            fed_heartbeat_s=0.0).validate()
        client = MemoryClient(broker)
        pipe = FusedPipeline(cfg, client=client, num_banks=num_banks)
        mine = roster[shard_of_keys(roster, K) == s]
        pipe.preload(mine)
        pipes.append((pipe, client, mine))
    return pipes


def _drain(agg, min_folds=0):
    folded = 0
    for _ in range(100):
        n = agg.poll(timeout_ms=50)
        folded += n
        if n == 0 and folded >= min_folds:
            break
    return folded


def test_gossip_end_to_end_two_workers(tmp_path):
    broker = MemoryBroker()
    K = 2
    roster, _ = generate_frames(0, 1, roster_size=6_000,
                                num_lectures=6, seed=3)
    agg = Aggregator(client=MemoryClient(broker), topic="fed-gossip",
                     num_shards=K, dead_after_s=30.0, precision=14)
    pipes = _federated_pipes(broker, tmp_path, K, roster)
    try:
        total = 0
        for s, (pipe, client, mine) in enumerate(pipes):
            rng = np.random.default_rng(100 + s)
            prod = client.create_producer(pipe.config.pulsar_topic)
            n = 0
            for _ in range(4):
                prod.send(frame_from_columns(synth_columns(
                    rng, 2_048, mine, 6, 0.1, invalid_base=200_000)))
                n += 2_048
            pipe.run(max_events=n, idle_timeout_s=0.5)
            pipe.snapshot()
            pipe.fed_flush()
            total += n
        _drain(agg, min_folds=K)
        assert agg.view.events == total
        assert agg.view.folded_deltas > 0  # fences really gossiped deltas
        # Zero false negatives over the FULL federation roster: the
        # global filter is the OR of every shard's preload frame.
        eng = QueryEngine(agg.mirror)
        assert eng.bf_exists(roster).all()
        # Registers equal the per-worker oracle merge, day-keyed.
        oracle = {}
        for pipe, _, _ in pipes:
            regs = np.asarray(pipe.state.hll_regs)
            for day, bank in pipe._bank_of.items():
                oracle[day] = (np.maximum(oracle[day], regs[bank])
                               if day in oracle else regs[bank].copy())
        got = agg.view.regs_by_day()
        assert set(got) == set(oracle)
        for day in oracle:
            assert (got[day] == oracle[day]).all(), day
        # Shard map learned both owners from gossip.
        assert sorted(filter(None, agg.shard_map.to_dict()["owners"])) \
            == ["w0", "w1"]
    finally:
        for pipe, _, _ in pipes:
            pipe.cleanup()
        agg.stop()


def test_dead_peer_chain_recovery(tmp_path):
    """A worker goes silent after making state durable: the aggregator
    declares it dead, orphans its shard at a bumped map version, and
    folds its on-disk base+delta chain so the global view keeps the
    peer's durable events."""
    broker = MemoryBroker()
    roster, _ = generate_frames(0, 1, roster_size=4_000,
                                num_lectures=4, seed=5)
    pipes = _federated_pipes(broker, tmp_path, 2, roster)
    agg = Aggregator(client=MemoryClient(broker), topic="fed-gossip",
                     num_shards=2, dead_after_s=0.4, precision=14)
    try:
        total = 0
        for s, (pipe, client, mine) in enumerate(pipes):
            rng = np.random.default_rng(40 + s)
            prod = client.create_producer(pipe.config.pulsar_topic)
            for _ in range(3):
                prod.send(frame_from_columns(synth_columns(
                    rng, 1_024, mine, 4, 0.1, invalid_base=200_000)))
            pipe.run(max_events=3 * 1_024, idle_timeout_s=0.5)
            pipe.snapshot()  # durable chain
            total += 3 * 1_024
        # The aggregator saw NO gossip yet; drain everything now.
        _drain(agg, min_folds=2)
        assert agg.view.events == total
        v0 = agg.shard_map.version
        # Workers stop gossiping (heartbeats disabled); after the
        # silence budget both are declared dead and their chains are
        # recovered — the merged view must not regress.
        time.sleep(0.5)
        dead = agg.check_liveness()
        assert sorted(dead) == ["w0", "w1"]
        assert agg.shard_map.version > v0
        assert agg.shard_map.owner_of(0) is None
        assert sorted(agg.recovered_chains) == ["w0", "w1"]
        assert agg.view.events == total  # chain == gossiped state
        stats = agg.stats()
        assert not stats["workers"]["w0"]["up"]
        eng = QueryEngine(agg.mirror)
        assert eng.bf_exists(roster).all()
    finally:
        for pipe, _, _ in pipes:
            pipe.cleanup()
        agg.stop()


def test_takeover_incarnation_supersedes(tmp_path):
    """A takeover worker (same id, restored chain, higher incarnation)
    supersedes the dead peer's counters; the dead peer's late frames
    are detected stale and never double-counted."""
    broker = MemoryBroker()
    roster, _ = generate_frames(0, 1, roster_size=4_000,
                                num_lectures=4, seed=6)
    mine = roster[shard_of_keys(roster, 2) == 0]

    def mkpipe():
        cfg = Config(
            bloom_filter_capacity=20_000, transport_backend="memory",
            pulsar_topic="events.s0",
            snapshot_dir=str(tmp_path / "w0"),
            snapshot_every_batches=2, fed_worker="w0", fed_shard=0,
            fed_shards=2, fed_gossip_topic="fed-gossip",
            fed_heartbeat_s=0.0).validate()
        client = MemoryClient(broker)
        return FusedPipeline(cfg, client=client, num_banks=8), client

    agg = Aggregator(client=MemoryClient(broker), topic="fed-gossip",
                     num_shards=2, dead_after_s=30.0, precision=14)
    pipe, client = mkpipe()
    rng = np.random.default_rng(7)
    prod = client.create_producer("events.s0")
    for _ in range(2):
        prod.send(frame_from_columns(synth_columns(
            rng, 1_024, mine, 4, 0.1, invalid_base=200_000)))
    pipe.run(max_events=2_048, idle_timeout_s=0.5)
    pipe.snapshot()
    late = None
    # Capture a "late" frame from the first incarnation before death:
    # re-publishing it later must not double-count.
    gos = pipe._fed
    late = gos._encode("heartbeat", 999_999)  # inflated counter claim
    pipe.cleanup()

    # Takeover: same worker id + snapshot dir; restore runs in the
    # constructor and publishes the chain state under the NEW
    # incarnation, with the restored total folded into every
    # subsequent durable/published count (_events_total).
    pipe2, client2 = mkpipe()
    try:
        # metrics.events is per-process; the chain-restored total
        # rides _events_restored so manifests/epochs/gossip stay
        # cumulative across the failover.
        assert pipe2.metrics.events == 0
        assert pipe2._events_restored == 2_048
        assert pipe2._events_total == 2_048
        prod2 = client2.create_producer("events.s0")
        prod2.send(frame_from_columns(synth_columns(
            rng, 1_024, mine, 4, 0.1, invalid_base=200_000)))
        pipe2.run(max_events=1_024, idle_timeout_s=0.5)
        pipe2.snapshot()
        pipe2.fed_flush()
        _drain(agg, min_folds=2)
        assert agg.view.events == 3_072
        inc2 = agg.view.workers["w0"].incarnation
        assert inc2 == pipe2._fed.incarnation
        # Replay the old incarnation's late frame: stale, no recount.
        agg.fold_frame(decode_frame(late))
        assert agg.view.stale_frames >= 1
        assert agg.view.events == 3_072
        assert agg.view.workers["w0"].incarnation == inc2
    finally:
        pipe2.cleanup()
        agg.stop()


def test_gossip_failure_defers_to_full_frame(tmp_path):
    """A failed gossip publish must not fail the fence; the next
    successful publish upgrades to a full frame (banks the aggregator
    may have missed are re-asserted)."""
    broker = MemoryBroker()
    roster, _ = generate_frames(0, 1, roster_size=3_000,
                                num_lectures=4, seed=8)
    agg = Aggregator(client=MemoryClient(broker), topic="fed-gossip",
                     num_shards=1, dead_after_s=30.0, precision=14)
    (pipe, client, mine), = _federated_pipes(
        broker, tmp_path, 1, roster)
    try:
        rng = np.random.default_rng(9)
        prod = client.create_producer(pipe.config.pulsar_topic)
        prod.send(frame_from_columns(synth_columns(
            rng, 1_024, mine, 4, 0.1, invalid_base=200_000)))
        # Break the producer under the gossip publisher.
        real_send = pipe._fed._producer.send
        pipe._fed._producer.send = _raise
        pipe.run(max_events=1_024, idle_timeout_s=0.5)
        pipe.snapshot()  # fence gossip fails silently
        assert pipe._fed.full_due
        pipe._fed._producer.send = real_send
        prod.send(frame_from_columns(synth_columns(
            rng, 1_024, mine, 4, 0.1, invalid_base=200_000)))
        pipe.run(max_events=1_024, idle_timeout_s=0.5)
        pipe.snapshot()  # upgraded to a full frame
        assert not pipe._fed.full_due
        _drain(agg, min_folds=1)
        assert agg.view.folded_fulls >= 2  # preload + upgrade
        assert agg.view.events == 2_048
    finally:
        pipe.cleanup()
        agg.stop()


def _raise(*a, **kw):
    raise ConnectionError("injected gossip outage")


# -- doctor rows -------------------------------------------------------------

def _fed_prom(lag_bucket_counts):
    lines = ["# TYPE attendance_fed_merge_lag_seconds histogram"]
    for le, c in lag_bucket_counts:
        lines.append(
            'attendance_fed_merge_lag_seconds_bucket{le="%s"} %d'
            % (le, c))
    lines += [
        'attendance_fed_peer_up{peer="w0"} 1',
        'attendance_fed_peer_up{peer="w1"} 0',
        "attendance_fed_merged_deltas_total 42",
        "attendance_fed_stale_frames_total 3",
        "attendance_fed_takeovers_total 1",
    ]
    return "\n".join(lines) + "\n"


def test_doctor_merge_lag_rows(tmp_path):
    from attendance_tpu.obs.slo import doctor_report

    prom = tmp_path / "metrics.prom"
    prom.write_text(_fed_prom(
        [(0.008, 90), (0.064, 99), (1.024, 100), ("+Inf", 100)]))
    text, ok = doctor_report([str(prom)], merge_lag_ceiling=2.0)
    assert ok
    assert "fed merge lag p99" in text
    assert "fed peers up at last scrape" in text and "1/2" in text
    assert "fed shard takeovers" in text
    # Breach: p99 sits in the (0.064, 1.024] bucket, above 0.01.
    text, ok = doctor_report([str(prom)], merge_lag_ceiling=0.01)
    assert not ok and "FAIL" in text
    # Without the flag the row is informational.
    text, ok = doctor_report([str(prom)])
    assert ok and "fed merge lag p99" in text
    # Ceiling set but NO lag histogram in the artifact: the gate must
    # fail loudly, not pass vacuously (the aggregator never folded).
    bare = tmp_path / "bare.prom"
    bare.write_text("attendance_events_total 5\n")
    text, ok = doctor_report([str(bare)], merge_lag_ceiling=5.0)
    assert not ok and "fed merge lag p99" in text and "FAIL" in text


def test_federate_cli_smoke(tmp_path, capsys):
    """The federate verb over a memory transport: starts, folds
    nothing, writes a stats file, exits by deadline."""
    from attendance_tpu.cli import main

    stats = tmp_path / "fed.json"
    main(["federate", "--transport-backend", "memory",
          "--fed-shards", "2", "--serve-seconds", "0.3",
          "--stats-json", str(stats), "--stats-every-s", "0.1"])
    import json
    doc = json.loads(stats.read_text())
    assert doc["shard_map"]["num_shards"] == 2
    assert doc["events"] == 0 and doc["workers"] == {}
    assert "serve_address" in doc
