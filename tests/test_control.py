"""Control-plane tests (ISSUE 20): the degradation-ladder state
machine (escalation/de-escalation ordering, dwell minimums, anti-flap
hysteresis), knob bounds/ladder safety (out-of-ladder shapes refused
and counted), the actuation-log schema round-trip, engine-level rung
application over a live registry, ingress admission spill/drain, the
SLO stage-name validation fix, and ``doctor --actuations``."""

import json
from types import SimpleNamespace

import pytest

from attendance_tpu import chaos, obs
from attendance_tpu.config import Config
from attendance_tpu.control import (
    ACTUATION_SCHEMA,
    ActuationLog,
    ControlEngine,
    DegradationLadder,
    IngressAdmission,
    Knob,
    KnobBoard,
    RUNGS,
    actuation_report,
    read_actuations,
)
from attendance_tpu.obs.incident import RULES, _actuation_matches, diagnose
from attendance_tpu.obs.slo import parse_slo


@pytest.fixture(autouse=True)
def _clean_planes():
    chaos.disable()
    obs.disable()
    yield
    chaos.disable()
    obs.disable()


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# DegradationLadder state machine
# ---------------------------------------------------------------------------


def test_ladder_escalates_monotonically_in_order():
    clk = Clock()
    lad = DegradationLadder(dwell_s=1.0, escalate_ticks=2,
                            clear_ticks=2, clock=clk)
    seen = []
    for _ in range(40):
        clk.t += 1.0
        moved = lad.tick(True)
        if moved is not None:
            seen.append(moved)
        if lad.rung == len(RUNGS) - 1:
            break
    # One rung at a time, strictly in ladder order, never skipping.
    assert seen == [1, 2, 3, 4]
    assert lad.mode == "shed"
    # Saturated: more pressure never overshoots.
    clk.t += 10.0
    assert lad.tick(True) is None
    assert lad.rung == 4


def test_ladder_deescalates_in_reverse_order():
    clk = Clock()
    lad = DegradationLadder(dwell_s=0.5, escalate_ticks=1,
                            clear_ticks=2, clock=clk)
    while lad.rung < 4:
        clk.t += 1.0
        lad.tick(True)
    seen = []
    for _ in range(40):
        clk.t += 1.0
        moved = lad.tick(False)
        if moved is not None:
            seen.append(moved)
        if lad.rung == 0:
            break
    assert seen == [3, 2, 1, 0]
    # Stable at normal.
    clk.t += 5.0
    assert lad.tick(False) is None


def test_ladder_dwell_minimum_blocks_fast_transitions():
    clk = Clock()
    lad = DegradationLadder(dwell_s=10.0, escalate_ticks=1,
                            clear_ticks=1, clock=clk)
    clk.t += 0.1
    assert lad.tick(True) == 1  # ladder starts settled: first is free
    # Streak satisfied but dwell not: held at rung 1.
    for _ in range(5):
        clk.t += 1.0
        assert lad.tick(True) is None
    assert lad.rung == 1
    clk.t += 10.0
    assert lad.tick(True) == 2


def test_ladder_transition_consumes_streak():
    clk = Clock()
    lad = DegradationLadder(dwell_s=0.1, escalate_ticks=3,
                            clear_ticks=3, clock=clk)
    for i in range(3):
        clk.t += 1.0
        moved = lad.tick(True)
    assert moved == 1
    # The NEXT escalation needs a fresh 3-tick pressure streak even
    # though dwell has long passed.
    clk.t += 1.0
    assert lad.tick(True) is None
    clk.t += 1.0
    assert lad.tick(True) is None
    clk.t += 1.0
    assert lad.tick(True) == 2


def test_ladder_flap_limit_holds():
    clk = Clock()
    lad = DegradationLadder(dwell_s=0.01, escalate_ticks=1,
                            clear_ticks=1, flap_limit=3, clock=clk)
    # Alternate pressure/clean fast enough to flap; all transitions
    # stay inside one 60 s window.
    transitions = 0
    for i in range(20):
        clk.t += 0.1
        if lad.tick(i % 2 == 0) is not None:
            transitions += 1
    assert transitions == 3  # capped by flap_limit
    assert lad.flap_holds > 0
    # Window expiry re-arms the ladder.
    clk.t += 61.0
    assert lad.tick(True) is not None


# ---------------------------------------------------------------------------
# Knob safety envelopes
# ---------------------------------------------------------------------------


def test_knob_clamps_to_bounds_and_counts():
    state = {"v": 10}
    k = Knob("snap", lambda: state["v"],
             lambda v: state.__setitem__("v", v), lo=4, hi=64)
    p = k.propose(1000)
    assert p.outcome == "clamped" and p.applied == 64
    assert state["v"] == 64
    p = k.propose(1)
    assert p.outcome == "clamped" and p.applied == 4
    assert state["v"] == 4
    assert k.clamped_total == 2
    p = k.propose(32)
    assert p.outcome == "applied" and state["v"] == 32
    assert k.propose(32).outcome == "noop"


def test_shape_knob_refuses_out_of_ladder():
    state = {"v": 1024}
    k = Knob("dispatch_size", lambda: state["v"],
             lambda v: state.__setitem__("v", v),
             ladder=(256, 512, 1024), shape_safe=True)
    p = k.propose(300)  # NOT a pre-warmed shape
    assert p.outcome == "refused" and p.applied is None
    assert state["v"] == 1024  # setter never ran
    assert k.refused_total == 1
    assert k.propose(512).outcome == "applied"
    assert k.step(+1) == 1024 and state["v"] == 512
    assert k.step(-1) == 256


def test_shape_knob_requires_ladder():
    with pytest.raises(ValueError):
        Knob("bad", lambda: 1, lambda v: None, shape_safe=True)


def test_knob_board_unknown_returns_none():
    b = KnobBoard()
    assert b.propose("nope", 1) is None


# ---------------------------------------------------------------------------
# Actuation log schema round-trip
# ---------------------------------------------------------------------------


def test_actuation_log_round_trip(tmp_path):
    path = tmp_path / "act.jsonl"
    log = ActuationLog(str(path))
    log.record(knob="audit_every", frm=1, to=8, outcome="applied",
               policy="degradation_ladder", action="widen_audit",
               direction="escalate", rung=1,
               conditions=["slo_burn", "circuit_open"],
               incident="inc-1-2-003")
    log.record(knob="dispatch_size", frm=1024, to=None,
               outcome="refused", policy="dispatch_resize",
               action="resize_dispatch", direction="adapt", rung=1,
               conditions=[], requested=300)
    log.close()
    records, problems = read_actuations(str(path))
    assert problems == []
    assert [r["seq"] for r in records] == [0, 1]
    assert records[0]["schema"] == ACTUATION_SCHEMA
    assert records[0]["conditions"] == ["circuit_open", "slo_burn"]
    assert records[0]["incident"] == "inc-1-2-003"
    assert records[1]["outcome"] == "refused"
    assert records[1]["requested"] == 300
    text, ok = actuation_report(str(path))
    assert ok
    assert "widen_audit" in text and "refused" in text


def test_actuation_log_detects_tamper_and_bad_seq(tmp_path):
    path = tmp_path / "act.jsonl"
    log = ActuationLog(str(path))
    for i in range(3):
        log.record(knob="k", frm=i, to=i + 1, outcome="applied",
                   policy="p", action="a", direction="adapt", rung=0,
                   conditions=[])
    log.close()
    lines = path.read_text().splitlines()
    doc = json.loads(lines[1])
    doc["seq"] = 0  # duplicate/regressed sequence
    doc["outcome"] = "mystery"
    lines[1] = json.dumps(doc)
    lines.append("{not json")
    path.write_text("\n".join(lines) + "\n")
    records, problems = read_actuations(str(path))
    assert any("not monotonic" in p for p in problems)
    assert any("unknown outcome" in p for p in problems)
    assert any("bad json" in p for p in problems)
    _text, ok = actuation_report(str(path))
    assert not ok


# ---------------------------------------------------------------------------
# Engine-level: rung application over a live registry
# ---------------------------------------------------------------------------


def _fake_pipe(snap_every=64):
    return SimpleNamespace(_audit_every=1, _snap_every=snap_every,
                           _temporal=None, consumer=None)


def _engine(tmp_path, clk, **kw):
    t = obs.enable(Config(control_log=str(tmp_path / "act.jsonl"),
                          metrics_interval_s=0.05))
    eng = t.control
    assert isinstance(eng, ControlEngine)
    eng.stop()  # drive tick() manually, like the incident suite
    eng2 = ControlEngine(t, str(tmp_path / "act2.jsonl"),
                         dwell_s=kw.pop("dwell_s", 1.0),
                         escalate_ticks=kw.pop("escalate_ticks", 2),
                         clear_ticks=kw.pop("clear_ticks", 2),
                         _clock=clk, **kw)
    return t, eng2


def test_engine_walks_ladder_and_restores(tmp_path):
    clk = Clock()
    t, eng = _engine(tmp_path, clk)
    pipe = _fake_pipe()
    eng.attach(pipe)
    sick = t.registry.gauge("attendance_circuit_state",
                            help="x", sink="store")
    sick.set(1.0)  # OPEN -> pressure on every tick
    for _ in range(30):
        clk.t += 1.0
        eng.tick(clk.t)
        if eng.ladder.rung == 4:
            break
    assert eng.ladder.rung == 4
    assert pipe._audit_every == 8          # rung 1
    assert pipe._snap_every == 64 * 4      # rung 2
    assert eng.admission.mode == "shed"    # rung 4 (no spill dir)
    sick.set(0.0)  # healed
    for _ in range(60):
        clk.t += 1.0
        eng.tick(clk.t)
        if eng.ladder.rung == 0:
            break
    assert eng.ladder.rung == 0
    assert pipe._audit_every == 1
    assert pipe._snap_every == 64
    assert eng.admission.mode == "pass"
    records, problems = read_actuations(eng.log.path)
    assert problems == []
    rungs = [r for r in records if r["knob"] == "ladder.rung"]
    assert [r["to"] for r in rungs[:4]] == [
        "audit_wide", "snap_stretch", "temporal_pause", "shed"]
    assert rungs[-1]["to"] == "normal"
    # Every record carries the triggering conditions.
    assert all("conditions" in r for r in records)
    assert any("circuit_open" in r["conditions"] for r in records)
    eng.log.close()


def test_engine_dispatch_shape_ladder_refuses(tmp_path):
    clk = Clock()
    t, eng = _engine(tmp_path, clk)
    consumer = SimpleNamespace(_dispatch_size=1024, lanes=[])
    consumer.set_dispatch_size = \
        lambda v: setattr(consumer, "_dispatch_size", int(v))
    pipe = _fake_pipe()
    pipe.consumer = consumer
    eng.attach(pipe)
    knob = eng.board.get("dispatch_size")
    assert knob is not None and knob.ladder == (256, 512, 1024)
    prop = knob.propose(300)
    rec = eng._record(prop, policy="dispatch_resize",
                      action="resize_dispatch", direction="adapt",
                      conditions=[], incident=None)
    assert prop.outcome == "refused"
    assert consumer._dispatch_size == 1024
    assert rec is not None and rec["outcome"] == "refused"
    fams = {name: members for name, _k, _h, members
            in t.registry.collect()}
    refused = fams.get("attendance_control_refused_total")
    assert refused and sum(m.value for m in refused) == 1
    eng.log.close()


def test_engine_spill_mode_with_dir(tmp_path):
    clk = Clock()
    t, eng = _engine(tmp_path, clk,
                     spill_dir=str(tmp_path / "ingress"))
    pipe = _fake_pipe()
    eng.attach(pipe)
    knob = eng.board.get("admission_mode")
    assert knob.ladder == ("pass", "spill", "shed")
    sick = t.registry.gauge("attendance_circuit_state",
                            help="x", sink="store")
    sick.set(1.0)
    for _ in range(30):
        clk.t += 1.0
        eng.tick(clk.t)
        if eng.ladder.rung == 4:
            break
    assert eng.admission.mode == "spill"
    eng.log.close()


# ---------------------------------------------------------------------------
# Ingress admission spill/drain
# ---------------------------------------------------------------------------


def test_admission_spill_drain_retire(tmp_path):
    adm = IngressAdmission(str(tmp_path / "spill"))
    assert adm.admit(b"frame0") == "pass"  # mode starts open
    adm.mode = "spill"
    assert adm.admit(b"frame1") == "spill"
    assert adm.admit(b"frame2") == "spill"
    assert adm.pending_count == 2
    batch = adm.drain_batch()
    assert [p[1] for p in batch] == [b"frame1", b"frame2"]
    assert adm.pending_count == 0
    paths = [p for p, _ in batch]
    assert all(p.exists() for p in paths)  # retire is the caller's
    IngressAdmission.retire(paths)
    assert not any(p.exists() for p in paths)


def test_admission_adopts_crashed_spill(tmp_path):
    d = tmp_path / "spill"
    adm = IngressAdmission(str(d))
    adm.mode = "spill"
    adm.admit(b"orphan")
    # New process over the same dir: the orphan must replay first.
    adm2 = IngressAdmission(str(d))
    assert adm2.pending_count == 1
    assert adm2.drain_batch()[0][1] == b"orphan"


def test_admission_shed_without_dir():
    adm = IngressAdmission("")
    adm.mode = "shed"
    assert adm.admit(b"x") == "shed"
    assert adm.shed_total == 1


# ---------------------------------------------------------------------------
# Satellites: SLO stage validation, diagnosis action wiring, doctor verb
# ---------------------------------------------------------------------------


def test_parse_slo_rejects_unknown_stage():
    with pytest.raises(ValueError, match="unknown stage"):
        parse_slo("persst_p99<=0.1")
    with pytest.raises(ValueError, match="unknown stage"):
        Config(slo=["bogus_p99<=1"]).validate()
    # Known stages and aliases still parse.
    assert parse_slo("dequeue_p99<=0.1").label_filter == \
        ("stage", "dequeue_wait")
    assert parse_slo("snapshot_blocked_p95<=1.0").quantile == 0.95
    Config(slo=["sketch_p50<=0.5", "throughput>=1"]).validate()


def test_every_rule_has_a_stable_action():
    assert all(r.action for r in RULES)
    ranked = diagnose({"circuit_open", "spill_growth"})
    assert ranked[0]["rule"] == "persist_sink_down"
    assert ranked[0]["action"] == "shed_ingress"


def test_actuation_matches_semantics():
    assert _actuation_matches("shed_ingress",
                              {"action": "shed_ingress"})
    assert not _actuation_matches("shed_ingress",
                                  {"action": "widen_audit"})
    # escalate_ladder is satisfied by any escalating ladder move.
    assert _actuation_matches(
        "escalate_ladder",
        {"action": "widen_audit", "policy": "degradation_ladder",
         "direction": "escalate"})
    assert not _actuation_matches(
        "escalate_ladder",
        {"action": "widen_audit", "policy": "degradation_ladder",
         "direction": "de-escalate"})


def test_doctor_actuations_verb(tmp_path, capsys):
    from attendance_tpu.cli import main as cli_main
    path = tmp_path / "act.jsonl"
    log = ActuationLog(str(path))
    log.record(knob="audit_every", frm=1, to=8, outcome="applied",
               policy="degradation_ladder", action="widen_audit",
               direction="escalate", rung=1, conditions=["slo_burn"])
    log.close()
    with pytest.raises(SystemExit) as exc:
        cli_main(["doctor", "--actuations", str(path)])
    assert exc.value.code in (0, None)
    assert "actuation replay: ok" in capsys.readouterr().out
    # A corrupt log exits 1.
    path.write_text(path.read_text() + "{broken\n")
    with pytest.raises(SystemExit) as exc:
        cli_main(["doctor", "--actuations", str(path)])
    assert exc.value.code == 1
    # A missing log exits 2.
    with pytest.raises(SystemExit) as exc:
        cli_main(["doctor", "--actuations", str(tmp_path / "no.jsonl")])
    assert exc.value.code == 2


def test_striped_consumer_lane_rescale_surface():
    """The lane_rescale policy's actuation surface: parking lanes is
    clamped to [1, n], parked lanes report in active_lanes, and
    re-opening resumes them."""
    from attendance_tpu.pipeline.lanes import StripedConsumer
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(ingress_lanes=3, batch_size=64,
                    pulsar_topic="lanes-ctl").validate()
    cons = StripedConsumer(config, MemoryClient(MemoryBroker()),
                           "lanes-ctl", "sub")
    try:
        assert cons.active_lanes == 3
        cons.set_active_lanes(1)
        assert cons.active_lanes == 1
        assert [lane.paused for lane in cons.lanes] == \
            [False, True, True]
        cons.set_active_lanes(0)  # clamped: never below one lane
        assert cons.active_lanes == 1
        cons.set_active_lanes(99)  # clamped to the configured width
        assert cons.active_lanes == 3
        assert not any(lane.paused for lane in cons.lanes)
    finally:
        cons.close()


def test_incident_report_cross_references_actuations(tmp_path):
    """`doctor --incident` + `--actuations`: the report says whether
    the recorded actuations matched the top-ranked rule's action."""
    from attendance_tpu.obs.incident import incident_report

    t = obs.enable(Config(incident_dir=str(tmp_path / "incidents")))
    eng = t.incidents
    eng.stop()
    eng.dir.mkdir(parents=True, exist_ok=True)
    t.registry.gauge("attendance_circuit_state", sink="disk").set(1.0)
    eng.tick()
    iid = eng.tick()  # sink_circuit_open -> action shed_ingress
    assert iid is not None

    path = tmp_path / "act.jsonl"
    log = ActuationLog(str(path))
    log.record(knob="admission_mode", frm="pass", to="shed",
               outcome="applied", policy="degradation_ladder",
               action="shed_ingress", direction="escalate", rung=4,
               conditions=["circuit_open"], incident=iid)
    log.close()
    text, ok = incident_report(eng.dir, actuation_log=str(path))
    assert ok
    assert "matched top rule (shed_ingress)" in text

    # A log with no matching action warns but does not fail the
    # replay (the bundle may predate the controller).
    miss = tmp_path / "miss.jsonl"
    log = ActuationLog(str(miss))
    log.record(knob="audit_every", frm=1, to=8, outcome="applied",
               policy="degradation_ladder", action="widen_audit",
               direction="escalate", rung=1, conditions=[],
               incident=iid)
    log.close()
    text, ok = incident_report(eng.dir, actuation_log=str(miss))
    assert ok
    assert "no recorded actuation for shed_ingress" in text


def test_config_control_flags_validated():
    with pytest.raises(ValueError, match="control_dwell_s"):
        Config(control_log="/tmp/a", control_dwell_s=0).validate()
    with pytest.raises(ValueError, match="control_spill_dir"):
        Config(control_spill_dir="/tmp/s").validate()
    Config(control_log="/tmp/a",
           control_spill_dir="/tmp/s").validate()
