"""Differential parity harness tests (SURVEY.md §4 "parity" tier).

Two layers:

* Hermetic: the harness drives the tpu and memory backends — two fully
  independent sketch implementations sharing only the key-normalization
  helper — through the exact reference call shapes, proving the drive
  logic and the assertions themselves without any external service.
* Redis-gated: the same harness against a real Redis Stack
  (``RedisSketchStore`` vs ``TpuSketchStore``), skipped cleanly when no
  server with RedisBloom answers at the configured host — run it with a
  local Redis Stack via ``python -m attendance_tpu.cli parity``.
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.parity import (
    RedisUnavailable, check_redis, run_parity)
from attendance_tpu.sketch.memory_store import MemorySketchStore
from attendance_tpu.sketch.tpu_store import TpuSketchStore


def _redis_or_skip():
    config = Config(sketch_backend="redis")
    try:
        check_redis(config, timeout_s=0.5)
    except RedisUnavailable as e:
        pytest.skip(f"no Redis Stack reachable: {e}")
    return config


def test_parity_tpu_vs_memory_hermetic():
    report = run_parity(
        TpuSketchStore(Config(sketch_backend="tpu")),
        MemorySketchStore(Config(sketch_backend="memory")),
        num_events=20_000, roster_size=5_000, num_lectures=3, seed=1)
    assert report.ok, report.summary()
    assert report.false_negatives_a == 0
    assert report.false_negatives_b == 0
    assert report.fpr_a <= report.fpr_limit
    assert report.hll_err_a <= 0.02
    assert report.hll_cross_err <= 0.02
    # All five insight surfaces of the report are populated.
    assert set(report.pfcounts_a) == set(report.exact_counts)


def test_parity_detects_broken_backend():
    """A backend that loses members must fail the no-false-negative
    gate — the harness is a real oracle, not a rubber stamp."""

    class LossyStore(MemorySketchStore):
        def bf_add_many(self, key, members):
            members = np.asarray(members)
            return super().bf_add_many(key, members[::2])  # drop half

    report = run_parity(
        TpuSketchStore(Config(sketch_backend="tpu")),
        LossyStore(Config(sketch_backend="memory")),
        num_events=5_000, roster_size=2_000, num_lectures=2, seed=2)
    assert not report.ok
    assert report.false_negatives_b > 0
    assert any("false negatives" in f for f in report.failures)


def test_check_redis_raises_cleanly_when_unreachable():
    config = Config(redis_host="127.0.0.1", redis_port=1)  # nothing there
    with pytest.raises(RedisUnavailable):
        check_redis(config, timeout_s=0.2)


def test_parity_against_real_redis_stack():
    """The VERDICT #5 deliverable: green against a live Redis Stack,
    hermetic skip otherwise."""
    from attendance_tpu.parity import run_redis_parity

    config = _redis_or_skip()
    report = run_redis_parity(config, num_events=20_000,
                              roster_size=5_000, num_lectures=3, seed=3)
    assert report.ok, report.summary()
