"""Worker script + shared workload for the 2-process DCN test.

Run as a subprocess by tests/test_multihost.py (never collected by
pytest — the name has no ``test_`` prefix):

    python tests/multihost_worker.py <process_id> <num_processes> \
        <coordinator_port> <out_json>

Each process joins a real ``jax.distributed`` CPU cluster (4 virtual
devices per process, gloo collectives over localhost TCP) and drives
the IDENTICAL deterministic workload through a
``make_multihost_mesh(num_shards=4)`` engine — dp spans the process
boundary, so every deferred-sync pmax/psum and the preload's
all-gather-OR actually cross "DCN". Results are written as JSON for
the test to compare against the single-process answer (the analogue of
the reference's competing consumers on one Pulsar Shared subscription,
reference attendance_processor.py:30-34).

Multi-controller convention: every process feeds the same full host
batch (numpy arrays; jit shards them over the mesh), and every process
executes the same program — the per-step validity AND rides "sp"
(intra-host), the replica union "dp" (cross-host).
"""

import hashlib
import json
import sys


def run_workload(mesh) -> dict:
    """The deterministic workload both the 2-process cluster and the
    single-process reference execute; returns JSON-serializable facts
    that must agree bit-for-bit across the two executions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from attendance_tpu.parallel.sharded import ShardedSketchEngine

    engine = ShardedSketchEngine(
        mesh, capacity=20_000, error_rate=0.01, num_banks=8,
        precision=14, layout="blocked", replica_sync="query")

    rng = np.random.default_rng(42)
    roster = np.arange(10_000, 30_000, dtype=np.uint32)
    engine.preload(roster)

    # 4 mixed batches: ~85% roster members, rest from a disjoint range.
    nvalid_total = 0
    total = 0
    exact = [set() for _ in range(8)]
    vhash = hashlib.sha256()
    for step_i in range(4):
        n = 4096
        take = rng.random(n) < 0.85
        keys = np.where(take, roster[rng.integers(0, len(roster), n)],
                        rng.integers(50_000, 80_000, n)).astype(np.uint32)
        banks = rng.integers(0, 8, n).astype(np.int32)
        if step_i % 2 == 0:
            valid = engine.step(keys, banks)
        else:
            # The packed word wire over the mesh (kw=17 covers 30k ids).
            kw = 17
            padded = engine.padded_size(n)
            words = np.full(padded, 0xFFFFFFFF, np.uint32)
            words[:n] = (banks.astype(np.uint32) << kw) | keys
            valid = engine.step_words(words, n, kw)
        # On a multi-process mesh the step kernels all_gather the
        # validity across "dp" (sharded.py host_readable), so the raw
        # vector is directly host-materializable here — the store-write
        # path FusedPipeline depends on. Hash it so the test proves the
        # per-event bits (not just the total) are identical to the
        # single-process execution.
        v_host = np.asarray(valid)
        assert v_host.shape == (n,), v_host.shape
        vhash.update(np.packbits(v_host).tobytes())
        nvalid_total += int(jax.jit(lambda v: jnp.sum(v.astype(jnp.int32))
                                    )(valid))
        total += n
        vmask = take  # ground truth (disjoint ranges, no FN possible)
        for b in range(8):
            exact[b].update(keys[vmask & (banks == b)].tolist())

    counts = [int(c) for c in engine.count_all()]
    # Membership over a fixed probe set (output of contains() is
    # host-materialized inside the engine — replicated across dp).
    probe = np.concatenate([roster[:512],
                            np.arange(60_000, 60_512, dtype=np.uint32)])
    member = engine.contains(probe)
    bits, regs = engine.get_state()
    return {
        "nvalid_total": nvalid_total,
        "total": total,
        "counts": counts,
        "exact": [len(s) for s in exact],
        "member_roster": int(member[:512].sum()),
        "member_invalid": int(member[512:].sum()),
        "bloom_sha": hashlib.sha256(bits.tobytes()).hexdigest(),
        "regs_sha": hashlib.sha256(regs.tobytes()).hexdigest(),
        "valid_sha": vhash.hexdigest(),
    }


def run_pipeline_workload(mesh) -> dict:
    """The FULL FusedPipeline on the mesh — broker frames in, sharded
    engine dispatch, columnar store writes OUT (the host-materialized
    validity that requires the multi-process all_gather in the step
    kernels — ADVICE r03: store writes used to require a
    single-process mesh). Multi-controller convention: every process
    feeds the identical deterministic frame stream and runs the
    identical lockstep of collective step calls; the wire format is
    pinned (auto mode adapts from TIMING, which would diverge across
    processes and deadlock the collectives)."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=20_000,
                    transport_backend="memory",
                    num_shards=mesh.shape["sp"],
                    num_replicas=mesh.shape["dp"],
                    wire_format="word")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8, mesh=mesh)
    num_events, batch = 8_192, 2_048
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=8_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=71)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=1.0)

    df = pipe.store.to_dataframe(deduplicate=False).sort_values(
        ["micros", "student_id"])
    # string keys: the worker's answers round-trip through JSON
    counts = {str(d): int(pipe.count(d)) for d in pipe.lecture_days()}
    vc = pipe.validity_counts()
    return {
        "pipe_events": pipe.metrics.events,
        "pipe_valid_sha": hashlib.sha256(
            np.packbits(df.is_valid.to_numpy(bool)).tobytes()
        ).hexdigest(),
        "pipe_counts": counts,
        "pipe_validity_counts": list(vc),
    }


def run_crash_workload(mesh, snap_dir: str) -> dict:
    """Phase A of the DCN crash/restore test (VERDICT r04 #5): the
    FusedPipeline on the 2-process mesh processes the FIRST HALF of a
    deterministic frame stream with checkpointing on (snapshot barriers
    mid-run; only process 0 writes the shared snapshot_dir), then
    returns — the parent SIGKILLs both processes, so whatever the
    snapshot captured is all that survives. The parent later restores
    onto a fresh single-process mesh and replays the unacked second
    half (what Pulsar redelivery would do) against a no-crash oracle."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=20_000,
                    transport_backend="memory",
                    num_shards=mesh.shape["sp"],
                    num_replicas=mesh.shape["dp"],
                    wire_format="word",
                    snapshot_dir=snap_dir, snapshot_every_batches=2)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8, mesh=mesh)
    num_events, batch = 16_384, 2_048
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=8_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=93)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    # First half only: the snapshot cadence (every 2 batches) barriers
    # mid-run; the second half stays unacked for the restore to replay.
    pipe.run(max_events=num_events // 2, idle_timeout_s=1.0)
    return {"crash_events": pipe.metrics.events,
            "crash_validity_counts": list(pipe.validity_counts())}


def main() -> None:
    proc_id, num_procs = int(sys.argv[1]), int(sys.argv[2])
    port, out_path = sys.argv[3], sys.argv[4]
    crash_snap_dir = sys.argv[5] if len(sys.argv) > 5 else None

    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_procs, process_id=proc_id)

    from attendance_tpu.parallel import multihost
    # The module-level guard must report the already-initialized
    # multi-process runtime (the FusedPipeline path calls it blindly).
    multihost._init_attempted = True
    assert multihost.init_distributed() is True
    assert jax.process_count() == num_procs

    # The DCN branch under test (parallel/multihost.py n_procs>1):
    # sp=4 fills each host's devices, dp=2 spans the process boundary.
    mesh = multihost.make_multihost_mesh(num_shards=4)
    assert dict(mesh.shape) == {"dp": num_procs, "sp": 4}, mesh.shape

    # The straddle invariant: 3 shards cannot divide 4 local devices.
    try:
        multihost.make_multihost_mesh(num_shards=3)
        raise AssertionError("straddling mesh must be rejected")
    except ValueError:
        pass

    if crash_snap_dir is not None:
        result = run_crash_workload(mesh, crash_snap_dir)
        result["process_id"] = proc_id
        result["process_count"] = jax.process_count()
        with open(out_path, "w") as f:
            json.dump(result, f)
        print(f"[p{proc_id}] SNAPPED", flush=True)
        # Hold the process (and its un-acked broker state) until the
        # parent SIGKILLs it — a real crash, no teardown runs.
        import time
        time.sleep(600)
        return

    result = run_workload(mesh)
    result.update(run_pipeline_workload(mesh))
    result["process_id"] = proc_id
    result["process_count"] = jax.process_count()
    with open(out_path, "w") as f:
        json.dump(result, f)
    print(f"[p{proc_id}] OK", flush=True)


if __name__ == "__main__":
    main()
