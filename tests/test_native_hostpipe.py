"""Native host runtime (hostpipe.c) — differential tests vs the numpy
path, plus the word-packed wire's step-program equivalence.

The native library is built on demand by attendance_tpu.native.build
(gcc is part of the baked toolchain); if no C compiler is available the
native-specific tests skip and the numpy fallback tests still run —
mirroring how the pipeline itself degrades.
"""

import numpy as np
import pytest

from attendance_tpu.native import load as load_native


@pytest.fixture(scope="module")
def hp():
    pipe = load_native()
    if pipe is None:
        pytest.skip("no C toolchain: native host runtime unavailable")
    return pipe


def _fixture(n=50_000, key_bits=22, lut_days=200, num_banks=64, seed=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << key_bits, n, dtype=np.uint32)
    day_base = 20260101
    days = rng.integers(day_base, day_base + lut_days, n, dtype=np.uint32)
    lut = np.full(1 << 14, -1, np.int32)
    lut[:lut_days] = rng.integers(0, num_banks, lut_days)
    return keys, days, lut, day_base


def test_max_key_matches_numpy(hp):
    keys, _, _, _ = _fixture()
    assert hp.max_key(keys) == int(keys.max())


def test_pack_words_matches_numpy(hp):
    from attendance_tpu.models.fused import pack_words

    keys, days, lut, base = _fixture()
    padded = 1 << 16
    kw = 22
    words, miss = hp.pack_words(keys, days, lut, base, kw, padded)
    assert miss == -1
    banks = lut[days - base]
    assert np.array_equal(words, pack_words(keys, banks, kw, padded))


def test_pack_bytes_matches_numpy(hp):
    keys, days, lut, base = _fixture()
    n, padded = len(keys), 1 << 16
    out, miss = hp.pack_bytes(keys, days, lut, base, 1, padded)
    assert miss == -1
    banks = lut[days - base]
    kv = out[:4 * padded].view(np.uint32)
    bv = out[4 * padded:]
    assert np.array_equal(kv[:n], keys)
    assert (kv[n:] == 0).all()
    assert np.array_equal(bv[:n], banks.astype(np.uint8))
    assert (bv[n:] == 0xFF).all()


def test_pack_words_reports_first_miss(hp):
    keys, days, lut, base = _fixture()
    days = days.copy()
    days[1234] = base + (1 << 14) + 7  # outside the LUT window
    words, miss = hp.pack_words(keys, days, lut, base, 22, 1 << 16)
    assert words is None and miss == 1234
    # unregistered (negative LUT) day is a miss too
    days2 = days.copy()
    days2[1234] = base
    lut2 = lut.copy()
    lut2[0] = -1
    hit = np.flatnonzero(days2 - base == 0)
    w2, m2 = hp.pack_words(keys, days2, lut2, base, 22, 1 << 16)
    assert w2 is None and m2 == hit[0]


def test_strided_atb1_record_input(hp):
    from attendance_tpu.models.fused import pack_words
    from attendance_tpu.pipeline.events import BINARY_DTYPE

    keys, days, lut, base = _fixture(n=10_000)
    rec = np.zeros(len(keys), dtype=BINARY_DTYPE)
    rec["student_id"] = keys
    rec["lecture_day"] = days
    assert hp.max_key(rec["student_id"]) == int(keys.max())
    words, miss = hp.pack_words(rec["student_id"], rec["lecture_day"],
                                lut, base, 22, 1 << 14)
    assert miss == -1
    banks = lut[days - base]
    assert np.array_equal(words, pack_words(keys, banks, 22, 1 << 14))


def test_delta_scan_matches_numpy(hp):
    """The split scan half of the native delta pack returns the numpy
    models.fused.delta_scan tuple exactly — the interchangeability the
    sharded per-replica packs rely on to share one width across
    natively- and numpy-scanned slices."""
    from attendance_tpu.models.fused import delta_scan

    keys, days, lut, base = _fixture(n=20_000)
    num_banks = 64
    scan, miss = hp.delta_scan(keys, days, lut, base, num_banks)
    assert miss == -1
    perm_n, counts_n, bases_n, deltas_n, needed_n = scan
    banks = lut[days - base]
    perm, counts, bases, deltas, needed = delta_scan(keys, banks,
                                                     num_banks)
    np.testing.assert_array_equal(perm_n, perm)
    np.testing.assert_array_equal(counts_n, counts)
    np.testing.assert_array_equal(bases_n, bases)
    np.testing.assert_array_equal(deltas_n, deltas)
    assert needed_n == needed


def test_bitpack_delta_interchangeable_with_numpy(hp):
    """bitpack_delta over a native OR a numpy scan produces the exact
    buffer numpy pack_delta builds (and refuses a too-narrow width the
    same way)."""
    from attendance_tpu.models.fused import (
        delta_scan, pack_delta, pick_delta_width)

    keys, days, lut, base = _fixture(n=20_000)
    num_banks, padded = 64, 1 << 15
    banks = lut[days - base]
    scan_np = delta_scan(keys, banks, num_banks)
    scan_nat, miss = hp.delta_scan(keys, days, lut, base, num_banks)
    assert miss == -1
    db = pick_delta_width(1, scan_np[-1])
    buf_ref, _ = pack_delta(keys, banks, db, padded, num_banks,
                            scan=scan_np)
    for scan in (scan_np, scan_nat):
        buf = hp.bitpack_delta(scan, db, padded, num_banks)
        np.testing.assert_array_equal(buf, buf_ref)
    # Too-narrow width: same refusal contract as numpy pack_delta.
    assert hp.bitpack_delta(scan_nat, scan_nat[-1] - 1, padded,
                            num_banks) is None
    assert pack_delta(keys, banks, scan_np[-1] - 1, padded, num_banks,
                      scan=scan_np) == (None, None)


def test_word_step_matches_byte_step():
    """fused_step_words == fused_step_bytes on identical inputs (the two
    wire formats must be semantically interchangeable)."""
    import jax
    import jax.numpy as jnp

    from attendance_tpu.models.bloom import bloom_add_packed
    from attendance_tpu.models.fused import (
        decode_counts, init_state, make_jitted_step_bytes,
        make_jitted_step_words, pack_words)

    state_a, params = init_state(capacity=10_000, num_banks=64)
    state_b, _ = init_state(capacity=10_000, num_banks=64)
    rng = np.random.default_rng(1)
    roster = rng.choice(1 << 20, 5000, replace=False).astype(np.uint32)
    pre = jax.jit(lambda b, k: bloom_add_packed(b, k, params))
    state_a = state_a._replace(bloom_bits=pre(state_a.bloom_bits, roster))
    state_b = state_b._replace(bloom_bits=pre(state_b.bloom_bits, roster))

    n, padded = 1000, 1024
    keys = np.where(rng.random(n) < 0.5, rng.choice(roster, n),
                    rng.integers(1 << 20, 1 << 21, n)).astype(np.uint32)
    banks = rng.integers(0, 64, n).astype(np.int32)

    buf = np.empty(5 * padded, np.uint8)
    kv = buf[:4 * padded].view(np.uint32)
    kv[:n] = keys
    kv[n:] = 0
    buf[4 * padded:][:n] = banks.astype(np.uint8)
    buf[4 * padded:][n:] = 0xFF
    state_a, valid_a = make_jitted_step_bytes(params, 1)(
        state_a, jnp.asarray(buf))

    kw = int(keys.max()).bit_length()
    words = pack_words(keys, banks, kw, padded)
    state_b, valid_b = make_jitted_step_words(params, kw)(
        state_b, jnp.asarray(words))

    assert np.array_equal(np.asarray(valid_a)[:n], np.asarray(valid_b)[:n])
    assert np.array_equal(np.asarray(state_a.hll_regs),
                          np.asarray(state_b.hll_regs))
    assert decode_counts(state_a.counts) == decode_counts(state_b.counts)


def test_pipeline_native_vs_numpy_identical(monkeypatch):
    """The FusedPipeline produces identical stores/sketches with the
    native host runtime and with ATP_NATIVE=0 (numpy)."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    def run(native: bool):
        import attendance_tpu.native as native_mod
        if not native:
            monkeypatch.setattr(native_mod, "_cached", None)
            monkeypatch.setattr(native_mod, "_tried", True)
        else:
            monkeypatch.setattr(native_mod, "_tried", False)
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory")
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        roster, frames = generate_frames(4096, 512, roster_size=2000,
                                         num_lectures=8, seed=11)
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=4096, idle_timeout_s=0.5)
        cols = pipe.store.to_columns()
        regs = np.asarray(pipe.state.hll_regs)
        counts = [pipe.count(d) for d in pipe.lecture_days()]
        return cols, regs, counts

    cols_np, regs_np, counts_np = run(native=False)
    cols_nat, regs_nat, counts_nat = run(native=True)
    for name in cols_np:
        assert np.array_equal(np.asarray(cols_np[name]),
                              np.asarray(cols_nat[name])), name
    assert np.array_equal(regs_np, regs_nat)
    assert counts_np == counts_nat


def test_pipeline_mixed_calendar_and_hashed_days():
    """Frames mixing calendar days with far-away hashed day codes (non-
    calendar lecture ids) must process correctly: the native pack falls
    back to the numpy path for the out-of-window days without losing
    events or miscounting."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.events import (
        AttendanceEvent, columns_from_events, encode_planar_batch)
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000,
                    transport_backend="memory")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(np.arange(100, 600, dtype=np.uint32))
    evs = []
    for i in range(300):
        lid = "LECTURE_20260302" if i % 2 == 0 else "PHYS101"
        evs.append(AttendanceEvent(100 + i, "2026-03-02T09:00:00", lid,
                                   True, "entry"))
    frame = encode_planar_batch(columns_from_events(evs))
    producer = client.create_producer(config.pulsar_topic)
    producer.send(frame)
    pipe.run(max_events=300, idle_timeout_s=0.5)
    cols = pipe.store.to_columns(deduplicate=False)
    assert len(cols["student_id"]) == 300
    assert np.asarray(cols["is_valid"], bool).all()  # all on roster
    days = sorted(pipe.lecture_days())
    assert len(days) == 2 and days[0] == 20260302
    # both banks countable, each ~150 uniques
    for day in days:
        assert abs(pipe.count(day) - 150) <= 5


def test_native_bypass_after_out_of_window_days():
    """A frame with out-of-LUT-window days arms the adaptive native
    bypass; it decays so the native path is re-probed later."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.events import (
        AttendanceEvent, columns_from_events, encode_planar_batch)
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000,
                    transport_backend="memory")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    if pipe._native is None:
        pytest.skip("no C toolchain: native host runtime unavailable")
    pipe.preload(np.arange(100, 600, dtype=np.uint32))

    def frame(lids):
        evs = [AttendanceEvent(100 + i, "2026-03-02T09:00:00",
                               lids[i % len(lids)], True, "entry")
               for i in range(64)]
        return encode_planar_batch(columns_from_events(evs))

    producer = client.create_producer(config.pulsar_topic)
    producer.send(frame(["LECTURE_20260302", "PHYS101"]))
    pipe.run(max_events=64, idle_timeout_s=0.3)
    assert pipe._native_skip == 32  # doomed-native bypass armed
    producer.send(frame(["LECTURE_20260302"]))
    pipe.run(max_events=128, idle_timeout_s=0.3)
    assert pipe._native_skip == 31  # decays per frame
    assert pipe.metrics.events == 128
