"""Live telemetry subsystem tests (attendance_tpu/obs).

Covers the registry semantics (counter monotonicity, histogram
power-of-2 bucket boundaries, gauge set/add/callback), the Prometheus
text exposition (golden file + format validity), the flight-recorder
ring (wrap order, SIGUSR1 dump, run-loop crash dump), the HTTP scrape
of a live fused run (the acceptance scenario), and the disabled-path
contract (no telemetry object anywhere when the flags are unset).
"""

import json
import logging
import os
import re
import signal
import time
import urllib.request
from pathlib import Path

import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.obs.exposition import (
    format_file, parse_prom, render)
from attendance_tpu.obs.recorder import FlightRecorder
from attendance_tpu.obs.registry import NUM_BUCKETS, Registry

GOLDEN = Path(__file__).parent / "data" / "obs_exposition.golden"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry is process-global; every test starts and ends bare."""
    obs.disable()
    yield
    obs.disable()


# -- registry semantics ------------------------------------------------------

def test_counter_monotonic():
    reg = Registry()
    c = reg.counter("c_total")
    c.inc()
    c.inc(41)
    assert c.value == 42
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 42  # the failed inc changed nothing


def test_gauge_set_add_and_callback():
    reg = Registry()
    g = reg.gauge("g")
    g.set(10)
    g.add(-3)
    assert g.value == 7
    g.set_function(lambda: 99)
    assert g.value == 99
    g.set(1)  # set clears the callback
    assert g.value == 1


def test_histogram_bucket_boundaries():
    """Power-of-2 buckets: scaled value u lands in bucket
    u.bit_length(), whose upper bound is 2**i / scale — observed at
    the exact boundaries."""
    reg = Registry()
    h = reg.histogram("h", scale=1.0)
    for v in (0, 0.5, 1, 2, 3, 4, 7, 8):
        h.observe(v)
    buckets, total, count = h.snapshot()
    assert count == 8 and total == 25.5
    assert buckets[0] == 2          # 0, 0.5  -> u=0, below 2^0
    assert buckets[1] == 1          # 1       -> [1, 2)
    assert buckets[2] == 2          # 2, 3    -> [2, 4)
    assert buckets[3] == 2          # 4, 7    -> [4, 8)
    assert buckets[4] == 1          # 8       -> [8, 16)
    assert h.bucket_bound(0) == 1.0 and h.bucket_bound(4) == 16.0
    # Over-range samples count toward +Inf (sum/count) ONLY — never a
    # finite bucket, which would claim the sample was below its bound.
    h.observe(2.0 ** 60)
    buckets, total, count = h.snapshot()
    assert count == 9 and sum(buckets) == 8
    assert buckets[NUM_BUCKETS - 1] == 0
    reg2 = Registry()
    h2 = reg2.histogram("of", scale=1.0)
    h2.observe(2.0 ** 60)
    lines = render(reg2).splitlines()
    finite = [l for l in lines if "_bucket" in l and "+Inf" not in l]
    assert all(l.endswith(" 0") for l in finite)
    assert [l for l in lines if "+Inf" in l][0].endswith(" 1")


def test_registry_identity_and_kind_mismatch():
    reg = Registry()
    a = reg.counter("x_total", wire="word")
    b = reg.counter("x_total", wire="word")
    assert a is b  # re-requesting a handle never double-registers
    assert reg.counter("x_total", wire="seg") is not a
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # same name, different kind


# -- exposition --------------------------------------------------------------

def _golden_registry() -> Registry:
    reg = Registry()
    c = reg.counter("attendance_events_total", help="Events processed")
    c.inc(41)
    c.inc()
    reg.counter("attendance_wire_frames_total", help="Frames per wire",
                wire="word").inc(3)
    reg.counter("attendance_wire_frames_total", wire="seg").inc(2)
    g = reg.gauge("attendance_queue_depth", help="Pending messages",
                  topic="t", subscription="s")
    g.set(7)
    h = reg.histogram("attendance_stage_latency_seconds",
                      help="Per-stage latency", stage="decode")
    h.observe(3e-6)
    h.observe(0.001)
    h.observe(0.5)
    return reg


def test_exposition_matches_golden_file():
    assert render(_golden_registry()) == GOLDEN.read_text()


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_+][a-zA-Z0-9_]*="[^"]*")*\})? '
    r'(-?\d+(\.\d+)?([eE][-+]?\d+)?|\+Inf|-Inf|NaN)$')


def test_exposition_is_valid_prometheus_text():
    """Every non-comment line is a well-formed sample; histograms are
    cumulative and consistent with _count."""
    text = render(_golden_registry())
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
    # Cumulative buckets never decrease; +Inf bucket == _count.
    samples = parse_prom(text)
    hist = [(labels, float(v)) for name, labels, v in samples
            if name == "attendance_stage_latency_seconds_bucket"]
    values = [v for _, v in hist]
    assert values == sorted(values)
    count = [float(v) for name, labels, v in samples
             if name == "attendance_stage_latency_seconds_count"][0]
    assert values[-1] == count == 3


def test_prom_table_formatter(tmp_path):
    path = tmp_path / "m.prom"
    path.write_text("# scrape 1.0\n" + render(_golden_registry()))
    table = format_file(str(path))
    assert "attendance_events_total" in table
    assert "count=3" in table  # histogram folded to count/sum/mean


def test_gauge_callback_raising_at_scrape_is_skipped_with_warning(
        caplog):
    """One bad device read (a raising health/queue callback) must not
    500 the endpoint or abort the prom append — its sample is skipped
    with a warning; every other metric still renders."""
    import logging

    reg = Registry()
    reg.counter("ok_total").inc(5)
    reg.gauge("bad_gauge", key="a").set_function(
        lambda: (_ for _ in ()).throw(RuntimeError("device gone")))
    reg.gauge("bad_gauge", key="b").set(3)
    with caplog.at_level(logging.WARNING,
                         logger="attendance_tpu.obs.exposition"):
        text = render(reg)
    assert "ok_total 5" in text
    assert 'bad_gauge{key="b"} 3' in text
    assert 'key="a"' not in text  # the raising sample is skipped...
    assert any("raised at scrape time" in r.message
               for r in caplog.records)  # ...loudly


def test_gauge_nan_inf_render_per_prometheus_text_rules():
    reg = Registry()
    reg.gauge("g", k="nan").set(float("nan"))
    reg.gauge("g", k="pinf").set(float("inf"))
    reg.gauge("g", k="ninf").set(float("-inf"))
    text = render(reg)
    assert 'g{k="nan"} NaN' in text
    assert 'g{k="pinf"} +Inf' in text
    assert 'g{k="ninf"} -Inf' in text
    for line in text.splitlines():
        if not line.startswith("#"):
            assert _SAMPLE_RE.match(line), line


def test_http_endpoint_survives_raising_gauge():
    t = obs.enable(Config(metrics_port=-1))
    t.registry.gauge("doomed").set_function(
        lambda: (_ for _ in ()).throw(OSError("no device")))
    t.events.inc(3)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{t.http_port}/metrics", timeout=10) as r:
        assert r.status == 200
        body = r.read().decode()
    assert "attendance_events_total 3" in body
    # No lying sample line: the raising gauge contributes at most its
    # HELP/TYPE comments, never a value.
    assert not [l for l in body.splitlines()
                if l.startswith("doomed ")]


# -- flight recorder ---------------------------------------------------------

def test_flight_ring_wraps_in_order():
    fr = FlightRecorder(4)
    for i in range(10):
        fr.record({"i": i})
    assert fr.total == 10
    assert [r["i"] for r in fr.snapshot()] == [6, 7, 8, 9]


def test_sigusr1_dump_is_wellformed_json(tmp_path):
    dump = tmp_path / "flight.json"
    t = obs.enable(Config(flight_recorder=8, flight_path=str(dump)))
    for i in range(3):
        t.record_batch(ts=float(i), events=i)
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.monotonic() + 5.0
    while not dump.exists() and time.monotonic() < deadline:
        time.sleep(0.01)
    doc = json.loads(dump.read_text())
    assert doc["reason"] == "SIGUSR1"
    assert doc["total_records"] == 3
    assert [r["events"] for r in doc["records"]] == [0, 1, 2]


def test_disable_restores_displaced_sigusr1_handler(tmp_path):
    """A leaked handler would dump a stale ring to a stale path after
    telemetry is torn down — disable() must restore what it displaced."""
    before = signal.getsignal(signal.SIGUSR1)
    obs.enable(Config(flight_recorder=4,
                      flight_path=str(tmp_path / "f.json")))
    assert signal.getsignal(signal.SIGUSR1) is not before
    obs.disable()
    assert signal.getsignal(signal.SIGUSR1) == before


def test_run_loop_crash_dumps_flight_ring(tmp_path):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    dump = tmp_path / "crash.json"
    config = Config(bloom_filter_capacity=2_000, flight_recorder=16,
                    flight_path=str(dump))
    obs.enable(config)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(1_024, 512, roster_size=1_000,
                                     num_lectures=2)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)

    def boom(block=0):
        raise RuntimeError("synthetic ack-path failure")

    pipe._drain_inflight = boom
    with pytest.raises(RuntimeError, match="synthetic"):
        pipe.run(max_events=1_024, idle_timeout_s=0.2)
    doc = json.loads(dump.read_text())
    assert doc["reason"] == "run-loop-exception"
    assert doc["records"], "crash dump carried no per-batch records"
    assert doc["records"][-1]["events"] == 512


# -- the acceptance scenario: scrape a live fused run ------------------------

def test_http_scrape_of_fused_run_exposes_contract_metrics(tmp_path):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000, metrics_port=-1,
                    flight_recorder=16,
                    flight_path=str(tmp_path / "flight.json"))
    t = obs.enable(config)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(4_096, 1_024, roster_size=4_000,
                                     num_lectures=4)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=4_096, idle_timeout_s=0.3)

    assert t.http_port
    with urllib.request.urlopen(
            f"http://127.0.0.1:{t.http_port}/metrics", timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()

    samples = {(n, l): float(v) for n, l, v in parse_prom(text)}
    # The scrape contract from the issue: events counter, per-wire
    # dispatch counter, queue-depth gauge, stage-latency histogram
    # with populated buckets.
    assert samples[("attendance_events_total", "")] == 4_096
    wire_total = sum(v for (n, l), v in samples.items()
                     if n == "attendance_wire_frames_total")
    assert wire_total == 4  # one per frame
    assert any(n == "attendance_queue_depth" and "subscription=" in l
               for (n, l), _ in samples.items())
    dispatch_count = [v for (n, l), v in samples.items()
                      if n == "attendance_stage_latency_seconds_count"
                      and 'stage="dispatch"' in l]
    assert dispatch_count and dispatch_count[0] == 4
    populated = [v for (n, l), v in samples.items()
                 if n == "attendance_stage_latency_seconds_bucket"
                 and 'stage="dispatch"' in l]
    assert max(populated) == 4  # cumulative buckets reach the count
    # Broker counters rode along.
    assert samples[("attendance_broker_received_messages_total",
                    f'subscription="{pipe.SUBSCRIPTION}",'
                    f'topic="{config.pulsar_topic}"')] >= 4


def test_file_reporter_appends_scrape_blocks(tmp_path):
    path = tmp_path / "metrics.prom"
    t = obs.enable(Config(metrics_prom=str(path),
                          metrics_interval_s=0.05))
    t.events.inc(7)
    time.sleep(0.2)
    obs.disable()  # stop() writes one final block
    text = path.read_text()
    assert text.count("# scrape ") >= 2
    samples = {n: v for n, l, v in parse_prom(text)}
    assert float(samples["attendance_events_total"]) == 7


def test_disabled_flags_leave_hot_paths_bare():
    """With every telemetry flag unset nothing is created anywhere:
    the pipelines hold None and pay one branch per hook."""
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.processor import AttendanceProcessor
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=1_000)
    pipe = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                         num_banks=8)
    proc = AttendanceProcessor(
        Config(sketch_backend="memory"),
        client=MemoryClient(MemoryBroker()))
    assert obs.get() is None
    assert pipe._obs is None and proc._obs is None


def test_cli_telemetry_verb_formats_both_artifacts(tmp_path, capsys):
    from attendance_tpu.cli import main

    fr = FlightRecorder(4)
    fr.record({"ts": 1.0, "events": 512, "wire": "word"})
    dump = fr.dump(tmp_path / "flight.json")
    main(["telemetry", str(dump)])
    out = capsys.readouterr().out
    assert "flight recorder dump" in out and "word" in out

    prom = tmp_path / "m.prom"
    prom.write_text(render(_golden_registry()))
    main(["telemetry", str(prom)])
    out = capsys.readouterr().out
    assert "attendance_events_total" in out and "42" in out


# -- label-cardinality guard (ISSUE 9) ---------------------------------------

def test_cardinality_cap_folds_overflow_into_unexported_sink(caplog):
    reg = Registry(max_series=3)
    handles = [reg.counter("leaky_total", day=str(d)) for d in range(3)]
    with caplog.at_level(logging.ERROR,
                         logger="attendance_tpu.obs.registry"):
        over_a = reg.counter("leaky_total", day="3")
        over_b = reg.counter("leaky_total", day="4")
    # Overflowing call sites share ONE sink of the right type — still
    # safe to record into, never exported.
    assert over_a is over_b
    assert over_a not in handles
    over_a.inc(5)  # the call-site contract survives overflow
    text = render(reg)
    assert text.count("leaky_total{") == 3  # capped, not ballooning
    assert "overflow" not in text
    errors = [r for r in caplog.records
              if "label-cardinality cap" in r.message]
    assert len(errors) == 1  # announced ONCE, not per registration


def test_cardinality_cap_is_per_name_and_reexport_safe():
    reg = Registry(max_series=2)
    reg.counter("a_total", k="1")
    reg.counter("a_total", k="2")
    sink = reg.counter("a_total", k="3")
    assert reg.counter("a_total", k="3") is sink  # stable sink handle
    # A DIFFERENT family is unaffected by a_total's overflow.
    assert render(reg).count("b_total") == 0
    reg.counter("b_total", k="1").inc()
    assert 'b_total{k="1"} 1' in render(reg)
    # Re-requesting an EXISTING label set still returns the real
    # metric, not the sink.
    assert reg.counter("a_total", k="1") is not sink


def test_series_self_gauge_tracks_registry_size():
    reg = Registry()
    base = [v for n, _, v in parse_prom(render(reg))
            if n == "attendance_metric_series_total"]
    assert base == ["1"]  # the self-gauge is its own only series
    reg.counter("x_total")
    reg.gauge("y", day="1")
    reg.gauge("y", day="2")
    now = [v for n, _, v in parse_prom(render(reg))
           if n == "attendance_metric_series_total"]
    assert now == ["4"]


def test_unlimited_registry_never_folds():
    reg = Registry(max_series=0)
    for d in range(2000):
        reg.counter("big_total", day=str(d))
    assert render(reg).count("big_total{") == 2000


# -- quantiles_from_cumulative edge cases (ISSUE 9) --------------------------

def test_quantiles_empty_histogram_is_nan():
    import math

    from attendance_tpu.obs.exposition import quantiles_from_cumulative

    assert all(math.isnan(v) for v in
               quantiles_from_cumulative([], (0.5, 0.99)))
    # All-zero cumulative counts (registered, never observed): same.
    assert all(math.isnan(v) for v in quantiles_from_cumulative(
        [(0.001, 0.0), (float("inf"), 0.0)], (0.5, 0.99)))


def test_quantiles_single_bucket_interpolates_from_zero():
    from attendance_tpu.obs.exposition import quantiles_from_cumulative

    (p50,) = quantiles_from_cumulative([(0.5, 4)], (0.5,))
    assert 0.0 < p50 <= 0.5
    (p100,) = quantiles_from_cumulative([(0.5, 4)], (1.0,))
    assert p100 == 0.5


def test_quantiles_inf_only_histogram_is_inf():
    import math

    from attendance_tpu.obs.exposition import quantiles_from_cumulative

    out = quantiles_from_cumulative([(float("inf"), 7)], (0.5, 0.99))
    assert all(math.isinf(v) for v in out)
    # Mass split across a finite bucket and +Inf: median is finite,
    # p99 lands in +Inf.
    p50, p99 = quantiles_from_cumulative(
        [(0.1, 5), (float("inf"), 10)], (0.5, 0.99))
    assert p50 <= 0.1 and math.isinf(p99)


# -- MetricsServer route mutation under concurrent scrape (ISSUE 9) ----------

def test_add_remove_route_under_concurrent_scrape():
    """The PR 7 teardown seam: the serve plane mounts and unmounts
    /query/* on the live process-global endpoint while scrapers are
    mid-flight. Every response must be a clean 200 (route present),
    404 (route absent), or — never — a hung/broken connection."""
    import threading
    import urllib.error

    reg = Registry()
    reg.counter("attendance_events_total", help="e").inc(1)
    from attendance_tpu.obs.exposition import MetricsServer

    server = MetricsServer(reg, port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    stop = threading.Event()
    failures = []

    def scraper(path):
        while not stop.is_set():
            try:
                with urllib.request.urlopen(base + path,
                                            timeout=5) as resp:
                    assert resp.status == 200
            except urllib.error.HTTPError as e:
                if e.code != 404:
                    failures.append(e)
            except Exception as e:  # noqa: BLE001 - any break is a fail
                failures.append(e)

    threads = [threading.Thread(target=scraper, args=(p,))
               for p in ("/metrics", "/extra") for _ in range(2)]
    try:
        for t in threads:
            t.start()

        def handler(method, path, query, body):
            return (200, "text/plain", b"ok")

        deadline = time.time() + 1.5
        while time.time() < deadline:
            server.add_route("/extra", handler)
            server.remove_route("/extra")
        server.remove_route("/extra")  # idempotent on absent
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        server.stop()
    assert not failures, failures[:3]
