"""Chaos soak: producer -> socket broker -> fused pipeline under a
randomized fault schedule, judged against a no-fault oracle (CI gate).

Each seed drives one soak:

1. an **oracle** run — memory broker, no faults — over a deterministic
   frame backlog establishes ground truth (per-day HLL counts, deduped
   store rows, valid totals);
2. the **chaos** run replays the SAME backlog (plus a couple of
   deliberately poisoned frames) through a real in-process socket
   broker with the full fault plane armed — request drops, connection
   resets in both directions, duplicate publishes, in-flight
   corruption, persist-sink failures, snapshot-writer stalls and
   failures — all drawn from PRNG streams derived from the seed;
3. the run must satisfy the four invariants that define correctness
   here:

   * **bounded termination** — the pipeline drains and exits inside
     the per-seed deadline (no livelock);
   * **no acked event lost / fault-run == no-fault oracle** — final
     HLL counts, deduped rows, and valid totals equal the oracle's
     exactly (duplicates folded by idempotent sketches + read-time
     dedup; spilled batches drained by the healed circuit);
   * **zero Bloom false negatives** — the full-shadow audit counter
     stays 0;
   * **self-healing, not operator action** — with ``conn_reset``
     injected the transport reconnected (reconnects > 0, session
     resumes > 0); with ``persist_fail`` injected the circuit opened,
     then half-opened closed, and the spill buffer fully drained;
     poisoned frames landed in the quarantine (count and sha256 both
     matching) instead of livelocking the subscription;

4. ``doctor`` replays the run's own telemetry artifacts (prom
   exposition + alert log + quarantine dir) and must pass.

On failure the driver echoes the seed and the one-line replay command.
CI runs 3 fixed seeds + 1 ``GITHUB_RUN_ID``-derived seed, each bounded
at 90 s.
"""

from __future__ import annotations

import argparse
import hashlib
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_SPEC = ("drop=0.03,delay=2ms:0.03,dup=0.02,conn_reset=0.03,"
                "persist_fail=0.15,writer_stall=30ms:0.1,"
                "snap_fail=0.1,corrupt=0.02")
# The storage-rot + partition seed (``--spec rot``): post-fsync bit
# flips and torn writes in the snapshot chain, injected ENOSPC at the
# writer seam, and consume-side partition blackhole windows — on top
# of a thinner transport-fault baseline. persist_fail is OFF here on
# purpose: rot inside the spill buffer is detectable-but-lossy by
# contract (covered by tests/test_integrity.py), so mixing it in would
# turn the oracle-equality gate into a tautology-breaker instead of a
# corruption-detection proof.
ROT_SPEC = ("drop=0.02,dup=0.02,conn_reset=0.02,corrupt=0.02,"
            "snap_fail=0.05,writer_stall=20ms:0.05,"
            "disk_corrupt=0.08,torn_write=0.04,enospc=0.02,"
            "partition=600ms:0.05")
# The shm-transport soak (``--spec shm``, ISSUE 11): torn slots +
# stalled writer against the mmap ring, with a REAL SIGKILL of the
# consumer mid-ring and a cursor-resume recovery (run_shm_soak).
SHM_SPEC = "torn_slot=0.08,writer_stall=15ms:0.05"
# The temporal-plane soak (``--spec temporal``, ISSUE 14): a
# disordered (disorder <= allowed lateness) ordered-clock stream with
# a super-late tail, through a delta-checkpointing temporal pipeline
# that is SIGKILLed once its chain holds a delta (mid-window, between
# rotations), then restored in-process — the restored run's windowed
# estimates must equal the no-crash oracle EXACTLY, the day plane and
# store must show zero acked loss, and the late counters must have
# fired. Snapshot-writer faults only: transport faults that REORDER
# delivery (drop/dup/conn_reset redelivery) would displace events
# beyond any fixed lateness budget by design — the lateness margin
# here is sized for the one reordering this soak proves (the kill's
# own redelivery window), not for arbitrary transport chaos.
TEMPORAL_SPEC = "snap_fail=0.05,writer_stall=20ms:0.05"
TEMPORAL_PERIOD_S = 4.0
TEMPORAL_LATENESS_S = 8.0
TEMPORAL_TAIL = 64
NUM_EVENTS, BATCH = 32_768, 512
ROSTER, LECTURES = 10_000, 8
POISON_FRAMES = 2
DATA_SEED_BASE = 7_000  # frame-content seed space, disjoint per soak seed


def _frames(seed: int, wire: str = "binary"):
    from attendance_tpu.pipeline.loadgen import generate_frames

    roster, frames = generate_frames(
        NUM_EVENTS, BATCH, roster_size=ROSTER,
        num_lectures=LECTURES, invalid_fraction=0.1,
        seed=DATA_SEED_BASE + seed)
    if wire == "columnar":
        from attendance_tpu.pipeline.codec import encode_columnar_batch
        from attendance_tpu.pipeline.events import decode_planar_batch
        frames = [encode_columnar_batch(decode_planar_batch(f))
                  for f in frames]
    return roster, frames


def _poison_frames(seed: int, wire: str = "binary"):
    """Deterministically undecodable frames: bad-magic garbage (the
    classic quarantine workload) and, on the columnar wire, a COLW
    frame whose checksum no longer matches its body — persistent wire
    rot that must dead-letter LOUDLY after bounded retries, never fold
    as silently mutated events."""
    import numpy as np

    rng = np.random.default_rng(900_000 + seed)
    frames = [b"ATPX" + rng.bytes(64 + 32 * i)
              for i in range(POISON_FRAMES)]
    if wire == "columnar":
        from attendance_tpu.pipeline.codec import encode_columnar_batch
        cols = {
            "student_id": rng.integers(10_000, 20_000, 64,
                                       dtype=np.uint32),
            "lecture_day": np.full(64, 20_260_701, np.uint32),
            "micros": np.arange(64, dtype=np.int64) + 10 ** 15,
            "is_valid": np.ones(64, bool),
            "event_type": np.zeros(64, np.int8),
        }
        rotted = bytearray(encode_columnar_batch(cols))
        rotted[len(rotted) // 2] ^= 0x55
        frames.append(bytes(rotted))
    return frames


def _state(pipe) -> dict:
    counts = {int(d): pipe.count(int(d)) for d in pipe.lecture_days()}
    df = pipe.store.to_dataframe()
    return {"counts": counts, "rows": len(df),
            "valid": int(df.is_valid.sum())}


def _counter_total(registry, name: str) -> float:
    total = 0.0
    for fam_name, _kind, _help, members in registry.collect():
        if fam_name == name:
            total += sum(float(m.value) for m in members)
    return total


def _oracle(seed: int, wire: str = "binary") -> dict:
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(
        Config(bloom_filter_capacity=50_000,
               transport_backend="memory"),
        client=client, num_banks=LECTURES)
    roster, frames = _frames(seed, wire)
    frames = list(frames)
    pipe.preload(roster)
    producer = client.create_producer("attendance-events")
    for frame in frames:
        producer.send(frame)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=2.0)
    state = _state(pipe)
    pipe.cleanup()
    return state


def run_soak(seed: int, *, spec: str = DEFAULT_SPEC, workdir,
             max_seconds: float = 90.0, wire: str = "binary") -> dict:
    """One seeded soak; returns the report dict (report["ok"] is the
    verdict). Resets the chaos/obs process globals around itself so
    seeds run back to back in one process. ``wire="columnar"`` ships
    the SAME events as COLW compressed frames — the corrupt fault then
    exercises the checksum-reject -> poison path end to end (loud DLQ,
    never silent mutation; the oracle-equality gate IS the proof)."""
    from attendance_tpu import chaos, obs

    failures = []
    t_start = time.monotonic()

    def check(cond, label):
        if not cond:
            failures.append(label)

    chaos.disable()
    obs.disable()
    want = _oracle(seed, wire)

    work = Path(workdir) / f"seed-{seed}"
    work.mkdir(parents=True, exist_ok=True)
    prom = work / "metrics.prom"
    alerts = work / "alerts.jsonl"
    qdir = work / "quarantine"

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport import make_client
    from attendance_tpu.transport.socket_broker import BrokerServer

    server = BrokerServer().start()
    config = Config(
        bloom_filter_capacity=50_000,
        transport_backend="socket", socket_broker=server.address,
        chaos=spec, chaos_seed=seed,
        quarantine_dir=str(qdir),
        persist_spill_dir=str(work / "spill"),
        persist_breaker_failures=2, persist_breaker_cooldown_s=0.25,
        snapshot_dir=str(work / "snaps"), snapshot_mode="delta",
        snapshot_every_batches=4,
        max_redeliveries=3, retry_budget_s=10.0,
        audit_sample=1.0,
        metrics_prom=str(prom), metrics_interval_s=0.2,
        alert_log=str(alerts)).validate()
    # Enable telemetry from the MAIN thread (signal handlers and the
    # SLO engine belong here), so the worker thread only records.
    obs.enable(config)
    inj = chaos.ensure(config)

    pipe = FusedPipeline(config, num_banks=LECTURES)
    roster, frames = _frames(seed, wire)
    frames = list(frames)
    pipe.preload(roster)

    poisons = _poison_frames(seed, wire)
    pub_client = make_client(config)  # chaos-wrapped: faults on publish
    producer = pub_client.create_producer(config.pulsar_topic)
    interval = max(1, len(frames) // (POISON_FRAMES + 1))
    remaining = list(poisons)
    for i, frame in enumerate(frames):
        producer.send(frame)
        if remaining and (i + 1) % interval == 0:
            producer.send(remaining.pop(0))  # poison mid-backlog
    for p in remaining:
        producer.send(p)

    # Bounded termination: the run gets a hard deadline in a worker
    # thread; a livelocked pipeline fails the seed instead of hanging
    # the driver.
    done = threading.Event()
    errors = []

    # An ENOSPC hit parks the snapshot writer at the CAPPED 5s backoff
    # (by design: no ladder of full-base attempts into a full disk) —
    # during that park the broker's unacked in-flight bound stalls
    # delivery, so the idle window must outlast the cap. Two
    # consecutive failed disk attempts (enospc then snap_fail, ~1% of
    # barrier sequences under this spec) chain two capped backoffs:
    # cover that too, or the run exits with a healthy backlog queued.
    idle_s = 15.0 if inj.spec.enospc > 0 else 3.0

    def _run():
        try:
            pipe.run(idle_timeout_s=idle_s)
        except BaseException as exc:  # noqa: BLE001 — report, don't hang
            errors.append(exc)
        finally:
            done.set()

    worker = threading.Thread(target=_run, name="soak-pipeline",
                              daemon=True)
    worker.start()
    terminated = done.wait(timeout=max_seconds)
    check(terminated, "bounded termination (pipeline still running at "
                      f"{max_seconds:.0f}s — livelock)")
    check(not errors, f"pipeline raised: {errors!r}")

    report = {"seed": seed, "spec": spec, "oracle": want}
    if terminated and not errors:
        pipe.cleanup()  # drains the spill buffer through the breaker
        got = _state(pipe)
        report["chaos_state"] = got
        check(got == want,
              f"fault-run state diverged from oracle: {got} != {want}")

        # Zero Bloom false negatives (full-shadow audit).
        registry = obs.get().registry
        fn = _counter_total(registry,
                            "attendance_bloom_false_negatives_total")
        check(fn == 0, f"bloom false negatives: {fn}")

        # Self-healing evidence, injected vs observed.
        injected = {f"{site}/{fault}": n
                    for (site, fault), n in sorted(inj.injected.items())}
        report["injected"] = injected
        reconnects = _counter_total(registry,
                                    "attendance_reconnects_total")
        report["reconnects"] = reconnects
        if inj.injected_total("conn_reset"):
            check(reconnects > 0,
                  "conn_reset injected but no reconnects recorded")
        store = pipe.store
        if inj.injected_total("persist_fail"):
            check(getattr(store, "breaker", None) is not None
                  and store.breaker.opened_total > 0,
                  "persist_fail injected but the circuit never opened")
            check(store.breaker.state == "closed",
                  f"circuit ended {store.breaker.state!r}, not closed")
            check(store.spill_pending == 0,
                  f"{store.spill_pending} spilled batches stranded")
            report["circuit_opened"] = store.breaker.opened_total
            report["spilled"] = store.spilled_total
            report["drained"] = store.drained_total

        # Poison frames: dead-lettered into the quarantine, bytes
        # intact (sha256 match), none lost, none livelocked.
        from attendance_tpu.transport.quarantine import list_entries
        entries = list_entries(qdir)
        report["quarantined"] = len(entries)
        report["dead_lettered"] = pipe.metrics.dead_lettered
        # At-least-once dead-lettering: a dead-letter ACK lost to an
        # injected reset redelivers the poison frame into one more
        # bounded cycle, so >= (duplicates share a digest).
        check(pipe.metrics.dead_lettered >= len(poisons),
              f"dead_lettered={pipe.metrics.dead_lettered}, "
              f"expected >= {len(poisons)}")
        # The quarantine holds poison frames as RECEIVED — a delivery
        # that also caught the in-flight ``corrupt`` fault lands as
        # its (deterministic, involutive) corrupted variant. Every
        # entry must be a poison frame or its variant (a real frame
        # in here means the retry bound ate live data), and every
        # poison frame must appear at least once (none escaped).
        from attendance_tpu.chaos import ChaosInjector
        per_poison = [
            {hashlib.sha256(p).hexdigest(),
             hashlib.sha256(
                 ChaosInjector.corrupt_transform(p)).hexdigest()}
            for p in _poison_frames(seed, wire)]
        acceptable = set().union(*per_poison)
        got_digests = [e["sha256"] for e in entries]
        check(all(d in acceptable for d in got_digests),
              "non-poison frame quarantined (retry bound ate a real "
              f"frame): {got_digests}")
        check(all(any(d in digs for d in got_digests)
                  for digs in per_poison),
              "a poison frame never reached the quarantine")

        # Storage-rot gates (the integrity plane, active iff the spec
        # armed disk faults): every injection whose rot still sits on
        # disk must be DETECTED by scrub — 100%, no exceptions — and
        # the run above already proved the rot cost nothing (state ==
        # oracle: the writer's in-memory mirror, not the rotted files,
        # is what served the run).
        if inj.injected_total("disk_corrupt") \
                or inj.injected_total("torn_write"):
            from attendance_tpu.utils.integrity import (
                scrub_paths, surviving_disk_faults)
            surviving = surviving_disk_faults(inj.disk_faults)
            rows, _scrub_ok = scrub_paths([work])
            # "Accounted for" = flagged CORRUPT, or classified as an
            # ORPHAN: a rotted delta whose manifest write then failed
            # was never published — restore ignores it and its frames
            # redeliver, so orphan-rot is harmless by construction
            # (and must not be reported as a silent miss).
            flagged = {r.path for r in rows
                       if r.corrupt or r.status == "orphan"}
            missed = surviving - flagged
            check(not missed,
                  f"scrub missed injected disk rot: {sorted(missed)}")
            report["disk_faults_injected"] = len(inj.disk_faults)
            report["disk_rot_surviving"] = len(surviving)
            report["scrub_accounted"] = len(surviving & flagged)
        if inj.spec.partition > 0:
            # Consume-side blackhole windows: the broker retained
            # everything, so the oracle-equality gate above IS the
            # convergence proof; here we only assert the fault
            # actually fired (a partition seed that never partitions
            # proves nothing).
            check(inj.injected_total("partition") > 0,
                  "partition armed but no blackhole window opened")
            report["partition_windows"] = inj.injected_total(
                "partition")
        if inj.injected_total("enospc"):
            disk_full = _counter_total(
                registry, "attendance_snapshot_disk_full_total")
            check(disk_full > 0,
                  "enospc injected but the disk-full counter never "
                  "fired (writer mis-classified it)")
            report["enospc_hits"] = disk_full

        # Doctor gate over the run's own artifacts.
        t = obs.get()
        t.finalize_slo("soak-end")
        if t._reporter is not None:
            t._reporter._write_block()
        from attendance_tpu.obs.slo import doctor_report
        try:
            text, ok = doctor_report([str(prom), str(alerts)],
                                     quarantine_dir=str(qdir))
            report["doctor_ok"] = ok
            check(ok, "doctor verdict FAIL:\n" + text)
        except Exception as exc:  # noqa: BLE001
            check(False, f"doctor raised: {exc!r}")

    server.stop()
    obs.disable()
    chaos.disable()
    report["wall_s"] = round(time.monotonic() - t_start, 1)
    report["failures"] = failures
    report["ok"] = not failures
    return report


def _shm_worker_main(args) -> None:
    """The to-be-SIGKILLed half of the shm soak: consume the ring
    with delta checkpointing until the parent kills us (or the stream
    drains on the post-crash run)."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    config = Config(
        bloom_filter_capacity=50_000, ingress_wire="shm",
        shm_dir=args.shm_dir, shm_slots=16, shm_slot_bytes=1 << 15,
        snapshot_dir=args.snapshot_dir, snapshot_mode="delta",
        snapshot_every_batches=4).validate()
    roster, _ = _frames(args.seed)
    pipe = FusedPipeline(config, num_banks=LECTURES)
    pipe.preload(roster)
    print("worker ready", flush=True)
    pipe.run(idle_timeout_s=60.0)


def run_shm_soak(seed: int, *, workdir,
                 max_seconds: float = 120.0) -> dict:
    """The shm-transport soak (ISSUE 11): a chaos-armed producer
    (torn_slot + writer_stall at the ring's publish seam) feeds a
    consumer SUBPROCESS that is SIGKILLed mid-ring once its snapshot
    chain holds a delta; recovery restores the chain and resumes from
    the ring's durable cursor — the unacked tail redelivers, and the
    final state must equal the no-fault oracle exactly (the PR 4/5
    group-commit + resume contracts, with the mmap ring as the wire)."""
    import json as _json
    import signal
    import subprocess

    from attendance_tpu import chaos, obs

    failures = []
    t_start = time.monotonic()

    def check(cond, label):
        if not cond:
            failures.append(label)

    chaos.disable()
    obs.disable()
    want = _oracle(seed)

    work = Path(workdir) / f"shm-seed-{seed}"
    work.mkdir(parents=True, exist_ok=True)
    shm_dir = work / "rings"
    snap = work / "snaps"

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.shm_ring import ShmClient

    config = Config(
        bloom_filter_capacity=50_000, ingress_wire="shm",
        shm_dir=str(shm_dir), shm_slots=16, shm_slot_bytes=1 << 15,
        snapshot_dir=str(snap), snapshot_mode="delta",
        snapshot_every_batches=4,
        chaos=SHM_SPEC, chaos_seed=seed).validate()
    inj = chaos.ensure(config)

    roster, frames = _frames(seed)
    frames = list(frames)
    producer = ShmClient.from_config(config).create_producer(
        config.pulsar_topic)

    worker = subprocess.Popen(
        [sys.executable, str(Path(__file__).resolve()), "--shm-worker",
         "--shm-dir", str(shm_dir), "--snapshot-dir", str(snap),
         "--seed", str(seed)],
        stdout=subprocess.PIPE, text=True, cwd=str(REPO))
    report = {"seed": seed, "spec": SHM_SPEC, "oracle": want}
    try:
        check(worker.stdout.readline().strip() == "worker ready",
              "shm worker failed to start")

        # Publish with the fault plane armed; the ring's backpressure
        # paces us against the consumer (and stalls entirely while it
        # is dead — bounded by the send timeout).
        pub_done = threading.Event()
        pub_errors = []

        def publish():
            try:
                for f in frames:
                    producer.send(f, timeout_s=max_seconds)
            except BaseException as exc:  # noqa: BLE001
                pub_errors.append(exc)
            finally:
                pub_done.set()

        threading.Thread(target=publish, daemon=True).start()

        # SIGKILL the consumer the moment its chain holds a delta —
        # mid-ring by construction (acks lag the barriers).
        chain_path = snap / "CHAIN.json"
        deadline = time.monotonic() + max_seconds
        while time.monotonic() < deadline:
            try:
                if _json.loads(chain_path.read_text()).get("deltas"):
                    break
            except (FileNotFoundError, ValueError):
                pass
            if worker.poll() is not None:
                check(False, "shm worker exited before the kill")
                return dict(report, failures=failures, ok=False,
                            wall_s=round(time.monotonic() - t_start, 1))
            time.sleep(0.02)
        else:
            check(False, "no delta snapshot within the deadline")
            return dict(report, failures=failures, ok=False,
                        wall_s=round(time.monotonic() - t_start, 1))
        worker.send_signal(signal.SIGKILL)
        worker.wait()

        # Resume IN PROCESS: restore the chain, re-attach the ring —
        # the durable cursor redelivers exactly the unacked tail.
        from attendance_tpu.transport.shm_ring import ring_path
        ring = ring_path(shm_dir, config.pulsar_topic, 0)
        check(ring.exists(), "ring file vanished")
        pipe = FusedPipeline(config, num_banks=LECTURES)
        backlog = pipe.consumer.backlog() if not hasattr(
            pipe.consumer, "lanes") else None
        report["resume_backlog"] = backlog
        check(backlog is None or backlog > 0,
              "no unacked tail to redeliver (kill landed post-drain; "
              "timing gate mis-set)")
        pipe.run(idle_timeout_s=3.0)
        check(pub_done.wait(timeout=max_seconds),
              "publisher never finished (ring stuck full)")
        check(not pub_errors, f"publisher raised: {pub_errors!r}")
        pipe.run(idle_timeout_s=2.0)  # drain anything late
        got = _state(pipe)
        report["chaos_state"] = got
        pipe.cleanup()
        check(got == want,
              f"shm crash+resume diverged from oracle: {got} != {want}")

        injected = {f"{site}/{fault}": n
                    for (site, fault), n in sorted(inj.injected.items())}
        report["injected"] = injected
        check(inj.injected_total("torn_slot") > 0,
              "torn_slot armed but never fired")
        check(inj.injected_total("writer_stall") > 0,
              "writer_stall armed but never fired")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        chaos.disable()
        obs.disable()
    report["wall_s"] = round(time.monotonic() - t_start, 1)
    report["failures"] = failures
    report["ok"] = not failures
    return report


def _temporal_frames(seed: int):
    """(roster, frames): an ordered disordered stream (25% of events
    up to 2s late — well inside the 8s lateness budget) plus a
    super-late TAIL re-sending the first frame's (by then ancient)
    events, which must side-channel as dropped in oracle and chaos
    runs alike."""
    import numpy as np

    from attendance_tpu.pipeline.events import decode_planar_batch
    from attendance_tpu.pipeline.loadgen import (
        frame_from_columns, generate_frames)

    roster, frames = generate_frames(
        NUM_EVENTS, BATCH, roster_size=ROSTER,
        num_lectures=LECTURES, invalid_fraction=0.1,
        seed=DATA_SEED_BASE + seed, disorder_frac=0.25,
        late_max_s=2.0, ordered=True)
    frames = list(frames)
    head = decode_planar_batch(frames[0])
    tail = {k: np.array(v[:TEMPORAL_TAIL]) for k, v in head.items()}
    frames.append(frame_from_columns(tail))
    return roster, frames


def _temporal_config(snap_dir, **kw):
    from attendance_tpu.config import Config

    return Config(
        bloom_filter_capacity=50_000,
        temporal_period_s=TEMPORAL_PERIOD_S,
        allowed_lateness_s=TEMPORAL_LATENESS_S,
        temporal_ring_banks=128,
        snapshot_dir=str(snap_dir) if snap_dir else "",
        snapshot_mode="delta",
        snapshot_every_batches=4, **kw).validate()


def _temporal_state(pipe) -> dict:
    state = _state(pipe)
    state["windows"] = {str(k): v
                       for k, v in pipe.window_counts().items()}
    return state


def _temporal_oracle(seed: int) -> dict:
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    client = MemoryClient(MemoryBroker())
    # NO snapshot dir (like _oracle): a chain dir shared across seeds
    # or reruns would be RESTORED at init and pollute the oracle with
    # the previous run's state — the oracle's correctness contract is
    # the window math, not the chain.
    pipe = FusedPipeline(
        _temporal_config(None, transport_backend="memory"),
        client=client, num_banks=LECTURES)
    roster, frames = _temporal_frames(seed)
    pipe.preload(roster)
    producer = client.create_producer("attendance-events")
    for frame in frames:
        producer.send(frame)
    pipe.run(max_events=NUM_EVENTS + TEMPORAL_TAIL, idle_timeout_s=2.0)
    state = _temporal_state(pipe)
    state["stats"] = {k: v for k, v in pipe.temporal_stats().items()
                      if k != "topk"}
    pipe.cleanup()
    return state


def _temporal_worker_main(args) -> None:
    """The to-be-SIGKILLed half of the temporal soak: consume the
    socket broker with delta checkpointing + the temporal plane until
    the parent kills us."""
    from attendance_tpu import chaos
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    config = _temporal_config(
        args.snapshot_dir, transport_backend="socket",
        socket_broker=args.broker, chaos=TEMPORAL_SPEC,
        chaos_seed=args.seed)
    chaos.ensure(config)
    roster, _ = _temporal_frames(args.seed)
    pipe = FusedPipeline(config, num_banks=LECTURES)
    pipe.preload(roster)
    print("worker ready", flush=True)
    pipe.run(idle_timeout_s=60.0)


def run_temporal_soak(seed: int, *, workdir,
                      max_seconds: float = 120.0) -> dict:
    """The temporal soak (ISSUE 14): disordered stream + SIGKILL of a
    delta-checkpointing temporal worker once its chain holds a delta,
    in-process restore + drain, then the gates: restored window
    estimates EXACTLY equal the no-crash oracle's, zero acked loss
    (day counts / deduped rows / valid totals equal), late counters
    fired (the super-late tail side-channeled), rotations happened,
    and doctor passes with the watermark-lag ceiling."""
    import json as _json
    import signal
    import subprocess

    from attendance_tpu import chaos, obs

    failures = []
    t_start = time.monotonic()

    def check(cond, label):
        if not cond:
            failures.append(label)

    chaos.disable()
    obs.disable()
    want = _temporal_oracle(seed)

    work = Path(workdir) / f"temporal-seed-{seed}"
    work.mkdir(parents=True, exist_ok=True)
    snap = work / "snaps"
    prom = work / "metrics.prom"

    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport import make_client
    from attendance_tpu.transport.socket_broker import BrokerServer

    server = BrokerServer().start()
    roster, frames = _temporal_frames(seed)
    pub_config = _temporal_config(snap, transport_backend="socket",
                                  socket_broker=server.address)
    pub_client = make_client(pub_config)
    producer = pub_client.create_producer(pub_config.pulsar_topic)
    for frame in frames:
        producer.send(frame)

    worker = subprocess.Popen(
        [sys.executable, str(Path(__file__).resolve()),
         "--temporal-worker", "--broker", server.address,
         "--snapshot-dir", str(snap), "--seed", str(seed)],
        stdout=subprocess.PIPE, text=True, cwd=str(REPO))
    report = {"seed": seed, "spec": TEMPORAL_SPEC}
    try:
        check(worker.stdout.readline().strip() == "worker ready",
              "temporal worker failed to start")
        # SIGKILL the worker the moment its chain holds a delta —
        # mid-window by construction (acks lag the barriers, buckets
        # are mid-rotation across the whole stream).
        chain_path = snap / "CHAIN.json"
        deadline = time.monotonic() + max_seconds
        while time.monotonic() < deadline:
            try:
                if _json.loads(chain_path.read_text()).get("deltas"):
                    break
            except (FileNotFoundError, ValueError):
                pass
            if worker.poll() is not None:
                check(False, "temporal worker exited before the kill")
                return dict(report, failures=failures, ok=False,
                            wall_s=round(time.monotonic() - t_start,
                                         1))
            time.sleep(0.02)
        else:
            check(False, "no delta snapshot within the deadline")
            return dict(report, failures=failures, ok=False,
                        wall_s=round(time.monotonic() - t_start, 1))
        worker.send_signal(signal.SIGKILL)
        worker.wait()

        # Restore IN PROCESS: the chain re-seeds the bucket ring, the
        # broker's crash takeover redelivers the unacked tail (whose
        # event-time displacement the 8s lateness budget covers), and
        # the stream drains to the end — tail included.
        config = _temporal_config(
            snap, transport_backend="socket",
            socket_broker=server.address,
            metrics_prom=str(prom), metrics_interval_s=0.2)
        obs.enable(config)
        pipe = FusedPipeline(config, num_banks=LECTURES)
        pipe.run(idle_timeout_s=3.0)
        got = _temporal_state(pipe)
        stats = {k: v for k, v in pipe.temporal_stats().items()
                 if k != "topk"}
        report["chaos_state_rows"] = got["rows"]
        report["stats"] = stats
        pipe.cleanup()

        check(got["windows"] == want["windows"],
              "restored window estimates diverged from the no-crash "
              f"oracle: {got['windows']} != {want['windows']}")
        check(got["counts"] == want["counts"],
              f"day counts diverged: {got['counts']} != "
              f"{want['counts']}")
        check(got["rows"] == want["rows"]
              and got["valid"] == want["valid"],
              f"store rows/valid diverged: {got['rows']}/"
              f"{got['valid']} != {want['rows']}/{want['valid']}")
        # Late counters: oracle and chaos run both dropped the tail
        # (counter totals span worker+restored process, so gate the
        # restored process' >= share plus the oracle's exact count).
        check(want["stats"]["late_dropped"] >= TEMPORAL_TAIL,
              "oracle never dropped the super-late tail")
        check(stats["late_dropped"] >= TEMPORAL_TAIL,
              f"late-dropped counter never fired post-restore "
              f"({stats['late_dropped']})")
        check(stats["rotations"] > 0, "no bucket rotations observed")
        check(stats["buckets"] > 0, "no temporal buckets restored")

        # Doctor over the restored run's own artifacts, with the
        # watermark-lag gate (steady-state lag == allowed lateness).
        t = obs.get()
        t.finalize_slo("soak-end")
        if t._reporter is not None:
            t._reporter._write_block()
        from attendance_tpu.obs.slo import doctor_report
        try:
            text, ok = doctor_report(
                [str(prom)],
                watermark_lag_ceiling=TEMPORAL_LATENESS_S * 4)
            report["doctor_ok"] = ok
            check(ok, "doctor verdict FAIL:\n" + text)
        except Exception as exc:  # noqa: BLE001
            check(False, f"doctor raised: {exc!r}")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        pub_client.close()
        server.stop()
        obs.disable()
        chaos.disable()
    report["wall_s"] = round(time.monotonic() - t_start, 1)
    report["failures"] = failures
    report["ok"] = not failures
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, action="append", default=None,
                    help="soak seed (repeatable; default 1)")
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="chaos spec for the fault run ('rot' = the "
                    "storage-rot + partition spec: disk_corrupt/"
                    "torn_write/enospc/partition with scrub gates; "
                    "'shm' = the shared-memory ring soak: torn_slot/"
                    "writer_stall + a real SIGKILL of the ring "
                    "consumer and cursor-resume recovery)")
    ap.add_argument("--wire", choices=["binary", "columnar"],
                    default="binary",
                    help="event wire for the fault run: columnar "
                    "ships the same events as COLW compressed frames "
                    "(checksum-reject -> loud DLQ under corrupt)")
    ap.add_argument("--workdir", default="/tmp/chaos_soak")
    ap.add_argument("--max-seconds", type=float, default=90.0,
                    help="per-seed deadline (termination invariant)")
    ap.add_argument("--shm-worker", action="store_true",
                    help=argparse.SUPPRESS)  # subprocess entry
    ap.add_argument("--temporal-worker", action="store_true",
                    help=argparse.SUPPRESS)  # subprocess entry
    ap.add_argument("--shm-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--broker", default="", help=argparse.SUPPRESS)
    ap.add_argument("--snapshot-dir", default="",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.shm_worker:
        args.seed = (args.seed or [1])[0]
        _shm_worker_main(args)
        return 0
    if args.temporal_worker:
        args.seed = (args.seed or [1])[0]
        _temporal_worker_main(args)
        return 0
    if args.spec == "rot":
        args.spec = ROT_SPEC
    seeds = args.seed or [1]
    rc = 0
    for seed in seeds:
        if args.spec == "temporal":
            print(f"=== temporal chaos soak seed={seed}", flush=True)
            report = run_temporal_soak(
                seed, workdir=args.workdir,
                max_seconds=max(args.max_seconds, 120.0))
            summary = {k: v for k, v in report.items()
                       if k not in ("failures", "stats")}
            print(f"seed {seed}: {summary}", flush=True)
            if not report["ok"]:
                rc = 1
                for f in report["failures"]:
                    print(f"FAIL seed={seed}: {f}", flush=True)
                print("SOAK FAIL — replay with:\n  JAX_PLATFORMS=cpu "
                      f"python tools/chaos_soak.py --seed {seed} "
                      "--spec temporal", flush=True)
            else:
                print(f"PASS seed={seed} ({report['wall_s']}s)",
                      flush=True)
            continue
        if args.spec == "shm":
            print(f"=== shm chaos soak seed={seed}", flush=True)
            report = run_shm_soak(seed, workdir=args.workdir,
                                  max_seconds=max(args.max_seconds,
                                                  120.0))
            summary = {k: v for k, v in report.items()
                       if k not in ("failures", "oracle",
                                    "chaos_state")}
            print(f"seed {seed}: {summary}", flush=True)
            if not report["ok"]:
                rc = 1
                for f in report["failures"]:
                    print(f"FAIL seed={seed}: {f}", flush=True)
                print("SOAK FAIL — replay with:\n  JAX_PLATFORMS=cpu "
                      f"python tools/chaos_soak.py --seed {seed} "
                      "--spec shm", flush=True)
            else:
                print(f"PASS seed={seed} ({report['wall_s']}s)",
                      flush=True)
            continue
        print(f"=== chaos soak seed={seed} spec={args.spec!r} "
              f"wire={args.wire}", flush=True)
        report = run_soak(seed, spec=args.spec, workdir=args.workdir,
                          max_seconds=args.max_seconds,
                          wire=args.wire)
        summary = {k: v for k, v in report.items()
                   if k not in ("failures", "oracle", "chaos_state")}
        print(f"seed {seed}: {summary}", flush=True)
        if report["ok"]:
            print(f"PASS seed={seed} ({report['wall_s']}s)",
                  flush=True)
        else:
            rc = 1
            for f in report["failures"]:
                print(f"FAIL seed={seed}: {f}", flush=True)
            print(f"SOAK FAIL seed={seed} — replay with:\n  "
                  f"JAX_PLATFORMS=cpu python tools/chaos_soak.py "
                  f"--seed {seed} --spec '{args.spec}'", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
