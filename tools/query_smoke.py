"""Query-plane smoke (CI gate): serve from a LIVE ingest run.

One process, real concurrency, no mocks:

1. a fused pipeline ingests a binary backlog with delta checkpointing
   (the barriers publish read epochs) and the query plane serving on
   an ephemeral binary RPC port, full-shadow audited
   (``--audit-sample 1.0``) with telemetry artifacts in the workdir;
2. a reader thread fires mixed point (batch 1/64/4096 BF.EXISTS) and
   table (occupancy / attendance-rate / pfcount) batches over the RPC
   for the whole ingest — every sampled answer cross-checks against
   the exact shadow;
3. hard invariants: zero read-path false negatives, measured read FPR
   within the 1% budget, every occupancy answer internally consistent
   (a whole epoch, never a mix);
4. ``doctor`` replays the run's own prom + alert artifacts with the
   query-p99 latency ceiling and the read-staleness gauge gated.

Exit 0 = all gates pass. The workdir (serve log + artifacts) is
uploaded by CI on failure.
Run on CPU: ``JAX_PLATFORMS=cpu python tools/query_smoke.py``.
"""

from __future__ import annotations

import argparse
import json
import logging
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

NUM_EVENTS, BATCH = 262_144, 8_192
SEED = 47


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/query_smoke")
    ap.add_argument("--query-p99-ceiling", type=float, default=0.5,
                    help="doctor gate on the query-stage p99 (s)")
    ap.add_argument("--staleness-ceiling", type=float, default=30.0,
                    help="doctor gate on the read epoch's age at the "
                    "final scrape (s)")
    args = ap.parse_args()
    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s - %(levelname)s - %(message)s",
        handlers=[logging.StreamHandler(),
                  logging.FileHandler(work / "serve.log")])
    log = logging.getLogger("query_smoke")

    import numpy as np

    from attendance_tpu import obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.serve.rpc import QueryClient
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    prom = work / "serve.prom"
    alerts = work / "alerts.jsonl"
    config = Config(
        bloom_filter_capacity=50_000, transport_backend="memory",
        snapshot_dir=str(work / "snaps"), snapshot_every_batches=4,
        serve_port=-1, audit_sample=1.0, metrics_prom=str(prom),
        alert_log=str(alerts), read_staleness_ceiling_s=60.0)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=16)
    roster, frames = generate_frames(
        NUM_EVENTS, BATCH, roster_size=20_000, num_lectures=8,
        invalid_fraction=0.1, seed=SEED)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for frame in frames:
        producer.send(frame)

    rng = np.random.default_rng(SEED)
    mix = np.where(
        rng.random(1 << 15) < 0.5, rng.choice(roster, 1 << 15),
        rng.integers(1 << 31, 1 << 32, size=1 << 15,
                     dtype=np.uint32)).astype(np.uint32)
    stop = threading.Event()
    stats = {"point": 0, "tables": 0, "errors": []}

    def reader() -> None:
        qc = QueryClient(pipe.query_server.address)
        i = 0
        try:
            while not stop.is_set():
                for bs in (1, 64, 4096):
                    chunk = mix[(i * bs) % (1 << 14):][:bs]
                    qc.bf_exists(chunk)
                    stats["point"] += len(chunk)
                occ = qc.occupancy()
                rates = qc.attendance_rate()
                qc.pfcount(sorted(occ) or [0])
                # Each verb pins its OWN epoch, and a barrier may
                # publish between the two RPCs — but the day set only
                # ever grows, so consecutive epochs' tables must be
                # subset-related; anything else is a torn answer.
                if occ and not (set(rates) <= set(occ)
                                or set(occ) <= set(rates)):
                    stats["errors"].append(
                        f"rate table days {sorted(rates)} vs "
                        f"occupancy days {sorted(occ)}: neither is a "
                        "subset of the other")
                stats["tables"] += 3
                i += 1
        except Exception as exc:  # noqa: BLE001 - smoke must report
            stats["errors"].append(repr(exc))
        finally:
            qc.close()

    t_reader = threading.Thread(target=reader, daemon=True)
    t_reader.start()
    t0 = time.perf_counter()
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=1.0)
    wall = time.perf_counter() - t0
    stop.set()
    t_reader.join(timeout=15.0)

    reg = obs.get().registry
    read_fn = reg.counter(
        "attendance_query_false_negatives_total").value
    audited = reg.counter("attendance_query_audited_total").value
    try:
        read_fpr = float(reg.gauge(
            "attendance_query_measured_fpr").read())
    except Exception:
        read_fpr = float("nan")
    staleness = float(pipe.read_mirror.staleness_s())
    log.info("ingested %d events in %.2fs (%.0f ev/s) while serving "
             "%d point answers + %d tables; audited=%d read_fn=%d "
             "read_fpr=%s staleness=%.2fs",
             pipe.metrics.events, wall,
             pipe.metrics.events / max(wall, 1e-9), stats["point"],
             stats["tables"], audited, read_fn, read_fpr, staleness)
    pipe.cleanup()
    obs.disable()  # flush the final prom block before doctor reads it

    failures = list(stats["errors"])
    if pipe.metrics.events < NUM_EVENTS:
        failures.append(f"ingest incomplete: {pipe.metrics.events}"
                        f"/{NUM_EVENTS}")
    if stats["point"] == 0 or stats["tables"] == 0:
        failures.append("reader answered nothing — serve plane dead")
    if audited == 0:
        failures.append("read audit never sampled an answer")
    if read_fn != 0:
        failures.append(f"read-path false negatives: {read_fn}")
    import math
    if not math.isnan(read_fpr) and read_fpr > 0.01:
        failures.append(f"read-path measured FPR {read_fpr} > 0.01")

    doctor = subprocess.run(
        [sys.executable, "-m", "attendance_tpu.cli", "doctor",
         str(prom), str(alerts),
         "--query-p99-ceiling", str(args.query_p99_ceiling),
         "--staleness-ceiling", str(args.staleness_ceiling)],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    log.info("doctor verdict:\n%s", doctor.stdout.strip())
    if doctor.returncode != 0:
        failures.append(f"doctor exit {doctor.returncode}: "
                        f"{doctor.stderr.strip()[-500:]}")

    (work / "verdict.json").write_text(json.dumps({
        "events": pipe.metrics.events,
        "point_answers": stats["point"],
        "tables": stats["tables"],
        "audited": audited,
        "read_false_negatives": int(read_fn),
        "read_measured_fpr": (None if math.isnan(read_fpr)
                              else read_fpr),
        "staleness_s": (None if math.isnan(staleness) else staleness),
        "failures": failures,
    }, indent=2))
    if failures:
        for f in failures:
            log.error("FAIL: %s", f)
        return 1
    log.info("query smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
