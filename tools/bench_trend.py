"""Bench trend-regression gate (CI step): the committed ``BENCH_*.json``
trajectory must never silently regress.

Every era of this repo commits its acceptance artifact —
``BENCH_r05.json``, ``BENCH_FED_r08.json``, ... — and the ROADMAP
reasons from that trajectory, but nothing MACHINE-checks it: a PR that
costs 30% of socket throughput while adding a feature lands green.
This tool parses the whole committed trajectory and gates on headline
regressions, with one hard honesty rule:

**Only like-for-like hosts compare.** Bench numbers from a 2-core CI
runner and a dedicated TPU host differ by orders of magnitude for
reasons that are not regressions. Artifacts are grouped into series by
filename (``BENCH_FED_r08.json`` -> series ``FED``, round 8), ordered
by round, and two adjacent artifacts gate ONLY when they name the same
``metric`` and carry equal host fingerprints (the ``host`` dict
``bench.py`` stamps; the stable subset — cpu_count, device kind/
platform, device count — is compared, not the kernel build string).
Artifacts without a fingerprint (the pre-r08 era) or cross-host
transitions are reported as ``skipped (unfingerprinted)`` /
``skipped (host changed)`` rows — visible, never gating, never
silently dropped. When the ADJACENT transition doesn't compare, the
gate walks back to the newest comparable predecessor in the series:
an unfingerprinted artifact in the middle must not shield a
like-for-like regression spanning it.

Headline columns: ``value`` plus every top-level numeric key ending in
``_events_per_sec`` / ``_qps`` that both artifacts carry. A column
regresses when it drops by at least ``--max-regression`` (fraction,
default 0.10 — an exactly-10% drop FAILS) versus the newest comparable
predecessor. Higher-is-better is assumed for all gated columns; lower-
is-better diagnostics (lag, stall) are never gated here — doctor owns
those ceilings.

**Attribution diff (ISSUE 15).** Artifacts that carry the profiling
plane's ``attribution`` block (``bench.py --mode obs`` writes it:
per-stage self-time fractions + recompile counts + dispatch-gap
percentiles) get one more row on a FAILED transition: the top-3
per-stage self-time deltas by name — ``dispatch +18.2pp`` — so a
flagged headline regression names the stage that moved instead of
reporting a bare ratio. A recompile-count increase between
like-for-like artifacts is also named (it is the classic silent cause
of exactly this kind of drop).

Exit codes: 0 = no gated regression (including "nothing comparable"),
1 = at least one headline column regressed between like hosts,
2 = unreadable input. Run:

    python tools/bench_trend.py                   # repo root artifacts
    python tools/bench_trend.py --dir /tmp/copy --max-regression 0.05
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

REPO = Path(__file__).resolve().parent.parent

ARTIFACT_RE = re.compile(r"^BENCH(?:_(?P<series>[A-Z0-9]+))?_r"
                         r"(?P<round>\d+)\.json$")

# The host-fingerprint subset that decides like-for-like. platform()
# and the python patch level churn without changing what the hardware
# can do; these four are what the rates actually depend on.
HOST_KEYS = ("cpu_count", "device_kind", "device_platform",
             "num_devices")

HEADLINE_SUFFIXES = ("_events_per_sec", "_qps")


class Artifact:
    __slots__ = ("path", "series", "round", "metric", "host",
                 "columns", "attribution")

    def __init__(self, path: Path, series: str, rnd: int, metric: str,
                 host: Optional[dict], columns: Dict[str, float],
                 attribution: Optional[dict] = None):
        self.path = path
        self.series = series
        self.round = rnd
        self.metric = metric
        self.host = host
        self.columns = columns
        self.attribution = attribution


def _headline_columns(doc: dict) -> Dict[str, float]:
    """``value`` + every top-level scalar rate column. Nested dicts
    (per-round sections, link-bytes maps) are diagnostics, not
    headlines. A fraction-valued ``value`` (the obs artifact's
    overhead fraction) is LOWER-is-better and must not gate as a rate
    — its run's ``*_events_per_sec`` columns still do."""
    cols: Dict[str, float] = {}
    v = doc.get("value")
    if (isinstance(v, (int, float)) and math.isfinite(v)
            and doc.get("unit") != "fraction"):
        cols["value"] = float(v)
    for key, val in doc.items():
        if (isinstance(val, (int, float)) and not isinstance(val, bool)
                and math.isfinite(val)
                and any(key.endswith(s) for s in HEADLINE_SUFFIXES)):
            cols[key] = float(val)
    return cols


def load_artifact(path: Path) -> Optional[Artifact]:
    """One parsed artifact, or None (with a note) when the filename or
    body doesn't fit the trajectory shape. Both committed shapes load:
    the driver wrapper ``{"cmd": ..., "parsed": {...}}`` and the bare
    bench document."""
    m = ARTIFACT_RE.match(path.name)
    if m is None:
        return None
    doc = json.loads(path.read_text())
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "metric" not in doc:
        print(f"[trend] {path.name}: no 'metric' key — skipped")
        return None
    host = doc.get("host")
    attribution = doc.get("attribution")
    return Artifact(path, m.group("series") or "E2E",
                    int(m.group("round")), str(doc["metric"]),
                    host if isinstance(host, dict) else None,
                    _headline_columns(doc),
                    attribution if isinstance(attribution, dict)
                    else None)


def host_key(host: Optional[dict]) -> Optional[Tuple]:
    if not host:
        return None
    return tuple(host.get(k) for k in HOST_KEYS)


def attribution_deltas(prev: Optional[dict], cur: Optional[dict],
                       top: int = 3) -> List[str]:
    """Human-readable per-stage self-time deltas (percentage points)
    between two artifacts' attribution blocks, largest first, plus a
    recompile-count delta when it grew — the "name the stage" half of
    a flagged regression. Empty when either side lacks the block."""
    if not prev or not cur:
        return []
    old = prev.get("stages") or {}
    new = cur.get("stages") or {}
    if not isinstance(old, dict) or not isinstance(new, dict):
        return []
    deltas = []
    for stage in sorted(set(old) | set(new)):
        try:
            d = float(new.get(stage, 0.0)) - float(old.get(stage, 0.0))
        except (TypeError, ValueError):
            continue
        deltas.append((abs(d), stage, d))
    deltas.sort(reverse=True)
    out = [f"{stage} {d * 100:+.1f}pp" for _, stage, d in deltas[:top]
           if abs(d) >= 0.001]
    try:
        r_old = int((prev.get("recompiles") or {}).get("total", 0))
        r_new = int((cur.get("recompiles") or {}).get("total", 0))
        if r_new > r_old:
            out.append(f"recompiles {r_old}->{r_new}")
    except (TypeError, ValueError):
        pass
    return out


def compare(prev: Artifact, cur: Artifact, max_regression: float
            ) -> List[List[str]]:
    """Rows for one adjacent transition inside a series. Gating rows
    carry PASS/FAIL; non-comparable transitions carry one skip row."""
    base = f"{prev.path.name} -> {cur.path.name}"
    if prev.metric != cur.metric:
        return [[base, "-", "-", "-",
                 f"skipped (metric changed: {prev.metric} -> "
                 f"{cur.metric})"]]
    if prev.host is None or cur.host is None:
        return [[base, "-", "-", "-", "skipped (unfingerprinted)"]]
    if host_key(prev.host) != host_key(cur.host):
        return [[base, "-", "-", "-", "skipped (host changed)"]]
    rows: List[List[str]] = []
    shared = sorted(set(prev.columns) & set(cur.columns))
    if not shared:
        return [[base, "-", "-", "-", "skipped (no shared columns)"]]
    for col in shared:
        old, new = prev.columns[col], cur.columns[col]
        if old <= 0:
            continue
        drop = 1.0 - new / old
        # >= with an epsilon: an exactly-threshold drop gates (and
        # 1 - 90/100 is 0.0999... in floats).
        verdict = ("FAIL" if drop >= max_regression - 1e-9
                   else "PASS")
        rows.append([f"{base} {col}",
                     f"{old:,.1f} -> {new:,.1f}",
                     f"{-drop:+.1%}",
                     f"> -{max_regression:.0%}", verdict])
    if any(r[4] == "FAIL" for r in rows):
        # Name the stage, not just the ratio: one attribution row per
        # FAILED transition, from the profiling plane's per-stage
        # self-time fractions (when both artifacts carry the block).
        named = attribution_deltas(prev.attribution, cur.attribution)
        if named:
            rows.append([f"{base} top stage deltas",
                         "; ".join(named), "-", "-", "info"])
        elif prev.attribution is None or cur.attribution is None:
            rows.append([f"{base} top stage deltas",
                         "(no attribution block — rerun bench.py "
                         "--mode obs to profile)", "-", "-", "info"])
    return rows


def run_gate(paths: List[Path], max_regression: float) -> Tuple[str, bool]:
    from attendance_tpu.obs.exposition import _table

    artifacts = [a for a in (load_artifact(p) for p in sorted(paths))
                 if a is not None]
    if not artifacts:
        return "[trend] no BENCH_*.json artifacts found", True
    series: Dict[str, List[Artifact]] = {}
    for a in artifacts:
        series.setdefault(a.series, []).append(a)
    rows: List[List[str]] = []
    for name in sorted(series):
        arts = sorted(series[name], key=lambda a: a.round)
        if len(arts) == 1:
            rows.append([f"{arts[0].path.name}", "-", "-", "-",
                         "info (single artifact)"])
        for i, cur in enumerate(arts[1:], 1):
            # Gate against the NEWEST COMPARABLE predecessor, not just
            # the adjacent artifact: an unfingerprinted or cross-host
            # artifact in the middle of a series must not shield a
            # like-for-like regression spanning it. The adjacent
            # transition still gets its visible skip row when it is
            # the one that didn't compare.
            prev = arts[i - 1]
            if (prev.metric != cur.metric or prev.host is None
                    or cur.host is None
                    or host_key(prev.host) != host_key(cur.host)):
                rows.extend(compare(prev, cur, max_regression))
                for cand in reversed(arts[:i - 1]):
                    if (cand.metric == cur.metric
                            and cand.host is not None
                            and cur.host is not None
                            and host_key(cand.host)
                            == host_key(cur.host)):
                        rows.extend(compare(cand, cur, max_regression))
                        break
            else:
                rows.extend(compare(prev, cur, max_regression))
    failed = sum(1 for r in rows if r[4] == "FAIL")
    gated = sum(1 for r in rows if r[4] in ("PASS", "FAIL"))
    head = (f"bench trend: {len(artifacts)} artifact(s), "
            f"{gated} gated column transition(s), "
            f"max regression {max_regression:.0%}")
    table = _table(rows, ["transition", "values", "delta", "target",
                          "verdict"])
    tail = ("verdict: PASS" if failed == 0
            else f"verdict: FAIL ({failed} column(s) regressed)")
    return "\n".join([head, table, tail]), failed == 0


def main(argv=None) -> int:
    sys.path.insert(0, str(REPO))
    ap = argparse.ArgumentParser(
        description="gate the committed BENCH_*.json trajectory on "
        "headline-column regressions between like-for-like hosts")
    ap.add_argument("--dir", default=str(REPO),
                    help="directory holding the BENCH_*.json "
                    "trajectory (default: repo root)")
    ap.add_argument("--max-regression", type=float, default=0.10,
                    help="gated fraction: a headline column dropping "
                    "by at least this much vs its newest comparable "
                    "predecessor FAILS (default 0.10)")
    ap.add_argument("artifacts", nargs="*",
                    help="explicit artifact files (overrides --dir "
                    "globbing)")
    args = ap.parse_args(argv)
    if not (0.0 < args.max_regression < 1.0):
        print("[trend] --max-regression must be in (0, 1)")
        return 2
    paths = ([Path(p) for p in args.artifacts] if args.artifacts
             else sorted(Path(args.dir).glob("BENCH*.json")))
    try:
        text, ok = run_gate(paths, args.max_regression)
    except (OSError, ValueError) as e:
        print(f"[trend] unreadable artifacts: {e}")
        return 2
    print(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
