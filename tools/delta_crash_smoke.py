"""Cross-process delta-snapshot crash-recovery smoke (CI gate).

Drives the whole durability story with a REAL ``SIGKILL``, no mocks:

1. start a socket broker subprocess and publish a deterministic binary
   backlog;
2. spawn a worker process running the fused pipeline with
   ``--snapshot-mode=delta`` (plus live telemetry artifacts);
3. SIGKILL the worker once its snapshot chain holds at least one delta;
4. restore a fresh pipeline from the snapshot dir, drain the frames the
   broker requeued (crash takeover), and compare the final state
   against an uninterrupted in-process oracle over the same frames;
5. replay the worker's telemetry artifacts through ``doctor`` with a
   snapshot-stall ceiling.

Exit 0 = recovery lossless and doctor passed; anything else fails CI.
Run on CPU: ``JAX_PLATFORMS=cpu python tools/delta_crash_smoke.py``.
"""

from __future__ import annotations

import argparse
import json
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

NUM_EVENTS, BATCH = 65_536, 2_048
SEED = 83


def _frames():
    from attendance_tpu.pipeline.loadgen import generate_frames

    return generate_frames(NUM_EVENTS, BATCH, roster_size=10_000,
                           num_lectures=8, invalid_fraction=0.1,
                           seed=SEED)


def worker_main(args) -> None:
    """The to-be-killed half: consume from the broker with delta
    checkpointing + telemetry until the parent SIGKILLs us."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.socket_broker import SocketClient

    config = Config(bloom_filter_capacity=50_000,
                    transport_backend="socket",
                    socket_broker=args.broker,
                    snapshot_dir=args.snapshot_dir,
                    snapshot_mode="delta",
                    snapshot_every_batches=2,
                    metrics_prom=args.metrics_prom,
                    metrics_interval_s=0.2,
                    alert_log=args.alert_log)
    roster, _ = _frames()
    pipe = FusedPipeline(config, client=SocketClient(args.broker),
                         num_banks=8)
    pipe.preload(roster)
    print("worker ready", flush=True)
    pipe.run(idle_timeout_s=60.0)  # parent kills us mid-stream


def _state(pipe) -> dict:
    counts = {int(d): pipe.count(int(d)) for d in pipe.lecture_days()}
    df = pipe.store.to_dataframe()
    return {"counts": counts, "rows": len(df),
            "valid": int(df.is_valid.sum())}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/delta_crash_smoke")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--broker", default="")
    ap.add_argument("--snapshot-dir", default="")
    ap.add_argument("--metrics-prom", default="")
    ap.add_argument("--alert-log", default="")
    ap.add_argument("--stall-ceiling", type=float, default=5.0,
                    help="doctor snapshot-stall p99 gate (generous: "
                    "shared CI runners)")
    args = ap.parse_args()
    if args.worker:
        worker_main(args)
        return 0

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    snap = work / "snaps"
    prom = work / "metrics.prom"
    alerts = work / "alerts.jsonl"

    from attendance_tpu.transport.socket_broker import spawn_broker

    broker_proc, addr = spawn_broker(cwd=REPO)
    worker = None
    try:
        worker = subprocess.Popen(
            [sys.executable, str(Path(__file__).resolve()), "--worker",
             "--broker", addr, "--snapshot-dir", str(snap),
             "--metrics-prom", str(prom), "--alert-log", str(alerts)],
            stdout=subprocess.PIPE, text=True, cwd=str(REPO))
        assert worker.stdout.readline().strip() == "worker ready", \
            "worker failed to start"

        from attendance_tpu.transport.socket_broker import SocketClient

        roster, frames = _frames()
        frames = list(frames)
        client = SocketClient(addr)
        producer = client.create_producer("attendance-events")
        for f in frames:
            producer.send(f)

        # Kill the worker the moment its chain holds a delta (mid-run
        # by construction: acks lag the barriers, so whatever is not
        # yet durable redelivers below).
        chain_path = snap / "CHAIN.json"
        deadline = time.time() + 120
        while time.time() < deadline:
            if chain_path.exists() and json.loads(
                    chain_path.read_text()).get("deltas"):
                break
            if worker.poll() is not None:
                print("FAIL: worker exited before the kill")
                return 1
            time.sleep(0.02)
        else:
            print("FAIL: no delta snapshot within 120s")
            return 1
        worker.send_signal(signal.SIGKILL)
        worker.wait()
        print(f"killed worker mid-run; chain: "
              f"{json.loads(chain_path.read_text())}", flush=True)

        # Recover: restore + drain the requeued frames. The broker's
        # crash takeover requeues everything unacked when the killed
        # worker's connection dropped.
        from attendance_tpu.config import Config
        from attendance_tpu.pipeline.fast_path import FusedPipeline

        config = Config(bloom_filter_capacity=50_000,
                        transport_backend="socket", socket_broker=addr,
                        snapshot_dir=str(snap), snapshot_mode="delta",
                        snapshot_every_batches=2)
        pipe = FusedPipeline(config, client=SocketClient(addr),
                             num_banks=8)
        restored_events = sum(pipe.validity_counts())
        pipe.run(idle_timeout_s=3.0)
        got = _state(pipe)
        pipe.cleanup()

        # Uninterrupted oracle over the same deterministic frames.
        from attendance_tpu.transport.memory_broker import (
            MemoryBroker, MemoryClient)

        oclient = MemoryClient(MemoryBroker())
        oracle = FusedPipeline(
            Config(bloom_filter_capacity=50_000,
                   transport_backend="memory"),
            client=oclient, num_banks=8)
        oracle.preload(roster)
        oproducer = oclient.create_producer("attendance-events")
        for f in frames:
            oproducer.send(f)
        oracle.run(max_events=NUM_EVENTS, idle_timeout_s=2.0)
        want = _state(oracle)

        print(f"restored_events_at_boot={restored_events} "
              f"recovered={got} oracle={want}", flush=True)
        if got != want:
            print("FAIL: crash+restore diverged from the "
                  "uninterrupted oracle (acked events lost or "
                  "double-counted)")
            return 1
        print("recovery lossless; running doctor on the worker's "
              "artifacts", flush=True)
        doctor = subprocess.run(
            [sys.executable, "-m", "attendance_tpu.cli", "doctor",
             str(prom), str(alerts),
             "--snapshot-stall-ceiling", str(args.stall_ceiling)],
            cwd=str(REPO))
        if doctor.returncode != 0:
            print(f"FAIL: doctor exited {doctor.returncode}")
            return doctor.returncode
        print("PASS: delta-snapshot crash recovery + doctor gate")
        return 0
    finally:
        if worker is not None and worker.poll() is None:
            worker.kill()
            worker.wait()
        broker_proc.kill()
        broker_proc.wait()


if __name__ == "__main__":
    sys.exit(main())
