"""Bisect probe for the mesh-executable dispatch collapse (PARITY.md
r03 forensics): measures plain single-chip async dispatch latency
after each cumulative stage of ShardedSketchEngine usage. Run on the
tunneled chip; the collapse is process-permanent, so the FIRST stage
whose probe degrades is the trigger.

    python tools/collapse_probe.py [stages...]
"""
import pathlib
import sys, time
import numpy as np

# Run as a script from anywhere: the package lives one level up.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

def probe(n=60):
    import jax
    x = jax.device_put(np.arange(1024, dtype=np.float32))
    f = jax.jit(lambda v: v * 1.0001 + 1.0)
    y = f(x); y.block_until_ready()
    t0 = time.perf_counter()
    y = x
    for _ in range(n):
        y = f(y)
    y.block_until_ready()
    return (time.perf_counter() - t0) / n * 1e3

def main():
    stages = sys.argv[1:] or ["mesh", "init", "preload", "step"]
    from attendance_tpu.utils.cache import enable_compilation_cache
    import pathlib
    enable_compilation_cache(str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    print(f"device: {jax.devices()[0]}", flush=True)
    print(f"baseline: {probe():.3f} ms/dispatch", flush=True)

    from attendance_tpu.parallel.sharded import ShardedSketchEngine, make_mesh
    from attendance_tpu.models.fused import pack_words
    mesh = engine = None
    rng = np.random.default_rng(0)
    for st in stages:
        t0 = time.perf_counter()
        if st == "mesh":
            mesh = make_mesh(1, 1)
        elif st == "init":
            engine = ShardedSketchEngine(mesh, capacity=1_000_000,
                                         error_rate=0.01, num_banks=64,
                                         layout="blocked")
        elif st == "preload":
            roster = rng.choice(1 << 31, size=1_000_000,
                                replace=False).astype(np.uint32)
            engine.preload(roster)
        elif st == "step22":
            kw = 22
            bs = 1 << 16
            keys = rng.integers(0, 1 << 22, bs, dtype=np.uint32)
            banks = rng.integers(0, 64, bs, dtype=np.uint32)
            words = pack_words(keys, banks, kw, engine.padded_size(bs))
            v = engine.step_words(words, bs, kw)
            v.block_until_ready()
        elif st == "fused31":
            import jax.numpy as jnp
            from attendance_tpu.models.fused import (
                init_state, make_jitted_step_words)
            state, params = init_state(capacity=1_000_000, num_banks=64,
                                       layout="blocked")
            stepf = make_jitted_step_words(params, 31)
            bs = 1 << 16
            keys = rng.integers(0, 1 << 31, bs, dtype=np.uint32)
            banks = np.zeros(bs, dtype=np.uint32)  # 1-bit bank field
            w = jnp.asarray(pack_words(keys, banks, 31, bs))
            state, v = stepf(state, w)
            v.block_until_ready()
        elif st == "step":
            kw = 31
            bs = 1 << 16
            keys = rng.integers(0, 1 << 31, bs, dtype=np.uint32)
            # kw=31 leaves a 1-bit bank field: only bank 0 is
            # representable (bank values are irrelevant to the
            # pathology; pack_words refuses sentinel collisions).
            banks = np.zeros(bs, dtype=np.uint32)
            words = pack_words(keys, banks, kw, engine.padded_size(bs))
            v = engine.step_words(words, bs, kw)
            v.block_until_ready()
        elif st == "query":
            engine.contains(np.arange(100, dtype=np.uint32))
        elif st == "hist":
            engine.count_all()
        elif st.startswith("variant:"):
            build_and_run_variant(st.split(":", 1)[1])
            continue
        elif st.startswith("mini:"):
            mini(st.split(":", 1)[1])
            continue
        elif st.startswith("mini2:"):
            mini2(st.split(":", 1)[1])
            continue
        elif st.startswith("mini3:"):
            mini3(st.split(":", 1)[1])
            continue
        elif st.startswith("fixed:"):
            fixed_variant(st.split(":", 1)[1])
            continue
        dt = time.perf_counter() - t0
        print(f"after {st:8s} ({dt:6.1f}s): {probe():.3f} ms/dispatch",
              flush=True)

# ---------------------------------------------------------------------------
# Variant bisect: standalone step_words-equivalents with one property
# toggled each, run in a FRESH process per variant (collapse is
# process-permanent).  python tools/collapse_probe.py variant:<name>
# ---------------------------------------------------------------------------

def build_and_run_variant(name: str) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from attendance_tpu.models.bloom import (
        BLOCK_BITS, bloom_positions, derive_bloom_params)
    from attendance_tpu.models.fused import _bump_counts, pack_words
    from attendance_tpu.models.hll import hll_bucket_rank
    from attendance_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(1, 1)
    params = derive_bloom_params(1_000_000, 0.01, "blocked")
    precision, num_banks, kw = 14, 64, 31
    chunk = 1 * BLOCK_BITS
    m_alloc = ((params.m_bits + chunk - 1) // chunk) * chunk
    m_words = m_alloc // 32
    m_words_local = m_words
    m_local = m_words_local * 32
    regs_local = 1 << precision
    key_mask = jnp.uint32((1 << kw) - 1)
    sentinel = jnp.uint32((1 << (32 - kw)) - 1)

    if "kw22" in name:
        kw = 22
        key_mask = jnp.uint32((1 << kw) - 1)
        sentinel = jnp.uint32((1 << (32 - kw)) - 1)
    no_counts = "nocounts" in name
    no_hll = "nohll" in name
    no_pmin = "nopmin" in name
    no_donate = "nodonate" in name
    vma = "vma" in name          # check_vma default (True)
    plain = "plainjit" in name   # no shard_map at all
    compile_only = "compileonly" in name

    def kernel(bits_loc, regs_loc, counts_loc, words):
        keys = words & key_mask
        banks_u = words >> kw
        bank_idx = jnp.where(banks_u == sentinel, jnp.int32(-1),
                             banks_u.astype(jnp.int32))
        mask = bank_idx >= 0
        pos = bloom_positions(keys, params).astype(jnp.int32)
        if plain:
            lo = jnp.int32(0)
        else:
            lo = jax.lax.axis_index("sp").astype(jnp.int32) * m_local
        rel = pos - lo
        in_range = (rel >= 0) & (rel < m_local)
        word = bits_loc[jnp.clip(rel >> 5, 0, m_words_local - 1)]
        bit = (jnp.clip(rel, 0, m_local - 1) & 31).astype(jnp.uint32)
        probes = jnp.where(in_range, (word >> bit) & jnp.uint32(1),
                           jnp.uint32(1))
        partial = jnp.all(probes == jnp.uint32(1), axis=1)
        if no_pmin or plain:
            valid = partial
        else:
            valid = jax.lax.pmin(partial.astype(jnp.int32), "sp") == 1
        outs = [valid]
        if not no_hll:
            bucket, rank = hll_bucket_rank(keys, precision)
            bi = jnp.where(valid, bank_idx, -1)
            keep = (bucket >= 0) & (bucket < regs_local) & (bi >= 0) & mask
            flat = jnp.where(keep, bi * regs_local + bucket, regs_loc.size)
            regs = regs_loc.reshape(-1).at[flat].max(
                rank.astype(jnp.uint8), mode="drop").reshape(regs_loc.shape)
            outs.append(regs)
        if not no_counts:
            nv = jnp.sum((valid & mask).astype(jnp.uint32))
            nr = jnp.sum(mask.astype(jnp.uint32))
            outs.append(_bump_counts(counts_loc[0], nv, nr - nv)[None])
        return tuple(outs)

    counts_spec = P("dp")
    out_specs = [P("dp")]
    in_specs = (P("sp"), P("dp", None, "sp"), counts_spec, P("dp"))
    if not no_hll:
        out_specs.append(P("dp", None, "sp"))
    if not no_counts:
        out_specs.append(counts_spec)
    donate = () if no_donate else tuple(
        i for i, keep in ((1, not no_hll), (2, not no_counts)) if keep)
    if plain:
        step = jax.jit(kernel, donate_argnums=donate)
    else:
        step = jax.jit(jax.shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=tuple(out_specs), check_vma=vma),
            donate_argnums=donate)

    bits = jax.device_put(jnp.zeros((m_words,), jnp.uint32),
                          NamedSharding(mesh, P("sp")))
    regs = jax.device_put(jnp.zeros((1, num_banks, regs_local), jnp.uint8),
                          NamedSharding(mesh, P("dp", None, "sp")))
    counts = jax.device_put(np.zeros((1, 2, 2), np.uint32),
                            NamedSharding(mesh, P("dp")))
    rng = np.random.default_rng(0)
    bs = 1 << 16
    keys = rng.integers(0, 1 << 31, bs, dtype=np.uint32)
    nb_fit = (1 << (32 - kw)) - 1  # bank ids below the padding sentinel
    banks = rng.integers(0, max(1, min(64, nb_fit)), bs, dtype=np.uint32)
    words = jnp.asarray(pack_words(keys, banks, kw, bs))
    t0 = time.perf_counter()
    if compile_only:
        step.lower(bits, regs, counts, words).compile()
    else:
        out = step(bits, regs, counts, words)
        jax.block_until_ready(out)
    print(f"variant {name} ({time.perf_counter() - t0:.1f}s): "
          f"{probe():.3f} ms/dispatch", flush=True)


def mini(spec_name: str) -> None:
    """Minimal trigger probe: one jitted add over one mesh-annotated
    array. python tools/collapse_probe.py mini:<dp|sp|none|plain>"""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from attendance_tpu.parallel.sharded import make_mesh

    x_np = np.arange(1 << 16, dtype=np.uint32)
    if spec_name == "plain":
        x = jax.device_put(x_np)
    else:
        mesh = make_mesh(1, 1)
        spec = {"dp": P("dp"), "sp": P("sp"), "none": P(None)}[spec_name]
        x = jax.device_put(x_np, NamedSharding(mesh, spec))
    f = jax.jit(lambda v: v + jnp.uint32(1))
    y = f(x)
    y.block_until_ready()
    print(f"mini {spec_name}: {probe():.3f} ms/dispatch", flush=True)


def mini2(name: str) -> None:
    """Second-round minimal triggers:
    gather      — bits P('sp') gathered by idx P('dp')
    gatherplain — same gather, both args unsharded
    gathersame  — same gather, both P(None) on the mesh
    mixed       — elementwise over two arrays with different specs
    big         — elementwise over the 1.2M-word P('sp') array alone
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from attendance_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(1, 1)
    bits_np = np.zeros(1_198_368, np.uint32)
    idx_np = np.arange(1 << 16, dtype=np.int32)

    def put(a, spec):
        if spec == "plain":
            return jax.device_put(a)
        return jax.device_put(a, NamedSharding(mesh, spec))

    if name == "gather":
        bits, idx = put(bits_np, P("sp")), put(idx_np, P("dp"))
        f = jax.jit(lambda b, i: b[i])
        jax.block_until_ready(f(bits, idx))
    elif name == "gatherplain":
        bits, idx = put(bits_np, "plain"), put(idx_np, "plain")
        f = jax.jit(lambda b, i: b[i])
        jax.block_until_ready(f(bits, idx))
    elif name == "gathersame":
        bits, idx = put(bits_np, P(None)), put(idx_np, P(None))
        f = jax.jit(lambda b, i: b[i])
        jax.block_until_ready(f(bits, idx))
    elif name == "mixed":
        a, b = put(idx_np, P("sp")), put(idx_np, P("dp"))
        f = jax.jit(lambda x, y: x + y)
        jax.block_until_ready(f(a, b))
    elif name == "big":
        bits = put(bits_np, P("sp"))
        f = jax.jit(lambda b: b + jnp.uint32(1))
        jax.block_until_ready(f(bits))
    print(f"mini2 {name}: {probe():.3f} ms/dispatch", flush=True)


def mini3(name: str) -> None:
    """Ladder from the triggering plainjit variant down:
    l0 — exact plainjit-nohll-nocounts control (4 sharded args)
    l1 — only (bits, words) args
    l2 — l1, trivial validity (no bloom math, no gather)
    l3 — l1, cheap positions (no murmur), gather kept
    l4 — l1, murmur positions, NO gather (sum instead)
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from attendance_tpu.models.bloom import (
        BLOCK_BITS, bloom_positions, derive_bloom_params)
    from attendance_tpu.models.fused import pack_words
    from attendance_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(1, 1)
    params = derive_bloom_params(1_000_000, 0.01, "blocked")
    kw = 31
    chunk = BLOCK_BITS
    m_alloc = ((params.m_bits + chunk - 1) // chunk) * chunk
    m_words = m_alloc // 32
    m_local = m_words * 32
    key_mask = jnp.uint32((1 << kw) - 1)

    def contains(bits_loc, keys):
        pos = bloom_positions(keys, params).astype(jnp.int32)
        word = bits_loc[jnp.clip(pos >> 5, 0, m_words - 1)]
        bit = (jnp.clip(pos, 0, m_local - 1) & 31).astype(jnp.uint32)
        probes = (word >> bit) & jnp.uint32(1)
        return jnp.all(probes == jnp.uint32(1), axis=1)

    def k_l0(bits_loc, regs_loc, counts_loc, words):
        return contains(bits_loc, words & key_mask)

    def k_l1(bits_loc, words):
        return contains(bits_loc, words & key_mask)

    def k_l2(bits_loc, words):
        return (words & jnp.uint32(1)) == 0

    def k_l3(bits_loc, words):
        keys = words & key_mask
        pos = (keys % jnp.uint32(m_local)).astype(jnp.int32)
        word = bits_loc[pos >> 5]
        return ((word >> (pos & 31).astype(jnp.uint32))
                & jnp.uint32(1)) == 1

    def k_l4(bits_loc, words):
        pos = bloom_positions(words & key_mask, params)
        return jnp.sum(pos, axis=1)

    def k_l5(bits_loc, words):
        x = words * jnp.uint32(2654435761)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(2246822519)
        return x ^ (x >> 16)

    def k_l6(bits_loc, words):
        return words % jnp.uint32(977)

    def k_l7(bits_loc, words):
        return words // jnp.uint32(977)

    def k_l8(bits_loc, words):
        return words % jnp.uint32(1024)  # power of two: lowers to AND

    def k_l9(bits_loc, words):
        return words % jnp.uint32(m_local)  # big non-pow2 divisor

    def k_l10(bits_loc, words):
        # gather with computed (shift/AND) index, no division
        idx = ((words >> 7) & jnp.uint32((1 << 18) - 1)).astype(jnp.int32)
        return bits_loc[jnp.clip(idx, 0, m_words - 1)]

    def k_l11(bits_loc, words):
        # gather with modulo-computed index
        idx = (words % jnp.uint32(m_words)).astype(jnp.int32)
        return bits_loc[idx]

    def k_l12(bits_loc, words):
        return jnp.sum(words)  # scalar reduce over the sharded input

    def k_l13(bits_loc, words):
        i = jnp.arange(7, dtype=jnp.uint32)
        return jnp.sum(words[:, None] + i[None, :], axis=1)  # row reduce

    def k_l14(bits_loc, words):
        i = jnp.arange(7, dtype=jnp.uint32)
        return jnp.all((words[:, None] + i[None, :]) > 0, axis=1)

    def k_l15(bits_loc, words):
        # gather + VARIABLE per-element shift (amount from data)
        idx = (words % jnp.uint32(m_words)).astype(jnp.int32)
        w = bits_loc[idx]
        return (w >> (words & jnp.uint32(31))) & jnp.uint32(1)

    def k_l16(bits_loc, words):
        idx = (words % jnp.uint32(m_words)).astype(jnp.int32)
        return bits_loc[idx] == jnp.uint32(0)  # bool output

    def k_l17(bits_loc, words):
        # l3 without the variable shift
        keys = words & key_mask
        pos = (keys % jnp.uint32(m_local)).astype(jnp.int32)
        word = bits_loc[pos >> 5]
        return word == jnp.uint32(0)

    def k_l18(bits_loc, words):
        # no key_mask; int32 >> before gather
        pos = (words % jnp.uint32(m_local)).astype(jnp.int32)
        return bits_loc[pos >> 5] == jnp.uint32(0)

    def k_l19(bits_loc, words):
        # shift in uint32, cast after
        pos = words % jnp.uint32(m_local)
        return bits_loc[(pos >> 5).astype(jnp.int32)] == jnp.uint32(0)

    def k_l20(bits_loc, words):
        # key_mask kept, no shift
        keys = words & key_mask
        idx = (keys % jnp.uint32(m_words)).astype(jnp.int32)
        return bits_loc[idx] == jnp.uint32(0)

    def k_l23(bits_loc, words):
        # and + remainder, NO gather
        keys = words & key_mask
        return keys % jnp.uint32(m_words)

    def k_l24(bits_loc, words):
        # l11 padded with clean elementwise ops (size control)
        idx = (words % jnp.uint32(m_words)).astype(jnp.int32)
        x = bits_loc[idx]
        for _ in range(8):
            x = x + jnp.uint32(1)
            x = x ^ jnp.uint32(0x9E3779B9)
        return x == jnp.uint32(0)

    def k_l25(bits_loc, words):
        # mask via minimum instead of and (range info, no and op)
        keys = jnp.minimum(words, jnp.uint32((1 << 31) - 1))
        idx = (keys % jnp.uint32(m_words)).astype(jnp.int32)
        return bits_loc[idx] == jnp.uint32(0)

    def k_l26(bits_loc, words):
        return (words >> 1) % jnp.uint32(m_words)  # range via shift

    def k_l27(bits_loc, words):
        return (words & jnp.uint32(0xFFFFF)) % jnp.uint32(977)

    def k_l28(bits_loc, words):
        return (words & jnp.uint32(0xAAAAAAAA)) % jnp.uint32(m_words)

    def k_l29(bits_loc, words):
        return (words & key_mask) // jnp.uint32(m_words)  # div not rem

    def k_l30(bits_loc, words):
        # shift-based 31-bit extraction instead of AND
        keys = (words << 1) >> 1
        return keys % jnp.uint32(m_words)

    def k_l31(bits_loc, words):
        # the engine's exact subchain: mask -> murmur3 -> mod blocks
        from attendance_tpu.ops.murmur3 import murmur3_u32
        keys = words & key_mask
        h1 = murmur3_u32(keys, jnp.uint32(0x9747B28C))
        return h1 % jnp.uint32(18723)

    def k_l32(bits_loc, words):
        return (words & jnp.uint32((1 << 30) - 1)) % jnp.uint32(m_words)

    def k_l33(bits_loc, words):
        return (words & key_mask) % jnp.uint32(977)

    bits = jax.device_put(jnp.zeros((m_words,), jnp.uint32),
                          NamedSharding(mesh, P("sp")))
    regs = jax.device_put(jnp.zeros((1, 64, 1 << 14), jnp.uint8),
                          NamedSharding(mesh, P("dp", None, "sp")))
    counts = jax.device_put(np.zeros((1, 2, 2), np.uint32),
                            NamedSharding(mesh, P("dp")))
    rng = np.random.default_rng(0)
    bs = 1 << 16
    keys = rng.integers(0, 1 << 31, bs, dtype=np.uint32)
    banks = np.zeros(bs, dtype=np.uint32)  # kw=31: 1-bit bank field
    words = jnp.asarray(pack_words(keys, banks, kw, bs))
    if name == "l0":
        f = jax.jit(k_l0)
        jax.block_until_ready(f(bits, regs, counts, words))
    else:
        f = jax.jit({"l1": k_l1, "l2": k_l2, "l3": k_l3, "l4": k_l4, "l5": k_l5, "l6": k_l6, "l7": k_l7, "l8": k_l8, "l9": k_l9, "l10": k_l10, "l11": k_l11, "l12": k_l12, "l13": k_l13, "l14": k_l14, "l15": k_l15, "l16": k_l16, "l17": k_l17, "l18": k_l18, "l19": k_l19, "l20": k_l20, "l23": k_l23, "l24": k_l24, "l25": k_l25, "l26": k_l26, "l27": k_l27, "l28": k_l28, "l29": k_l29, "l30": k_l30, "l31": k_l31, "l32": k_l32, "l33": k_l33}[name])
        jax.block_until_ready(f(bits, words))
    print(f"mini3 {name}: {probe():.3f} ms/dispatch", flush=True)


def fixed_variant(name: str) -> None:
    """Candidate engine fix: division-free block mapping (multiply-high
    range reduction emulated in 16-bit limbs) + shift-based key
    extraction. names: fixed-kw31, fixed-kw22, fixed-full-kw31 (adds
    hll+counts+pmin under shard_map with donation)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from attendance_tpu.models.bloom import (
        BLOCK_BITS, derive_bloom_params, SEED_BLOOM_A, SEED_BLOOM_B,
        SEED_BLOCK)
    from attendance_tpu.models.fused import _bump_counts, pack_words
    from attendance_tpu.models.hll import hll_bucket_rank
    from attendance_tpu.ops.murmur3 import murmur3_u32
    from attendance_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(1, 1)
    params = derive_bloom_params(1_000_000, 0.01, "blocked")
    kw = 22 if "kw22" in name else 31
    full = "full" in name
    precision, num_banks = 14, 64
    m_alloc = ((params.m_bits + BLOCK_BITS - 1) // BLOCK_BITS) * BLOCK_BITS
    m_words = m_alloc // 32
    m_local = m_words * 32
    regs_local = 1 << precision
    num_blocks = params.m_bits // BLOCK_BITS
    sentinel = jnp.uint32((1 << (32 - kw)) - 1)

    def mulhi_u32(a, b_const: int):
        """(a * b) >> 32 without 64-bit ops: 16-bit limb products."""
        bl = jnp.uint32(b_const & 0xFFFF)
        bh = jnp.uint32(b_const >> 16)
        al = a & jnp.uint32(0xFFFF)
        ah = a >> 16
        ll = al * bl
        lh = al * bh
        hl = ah * bl
        hh = ah * bh
        mid = (ll >> 16) + (lh & jnp.uint32(0xFFFF)) + (
            hl & jnp.uint32(0xFFFF))
        return hh + (lh >> 16) + (hl >> 16) + (mid >> 16)

    def positions(keys):
        h1 = murmur3_u32(keys, SEED_BLOOM_A)
        h2 = murmur3_u32(keys, SEED_BLOOM_B) | jnp.uint32(1)
        h3 = murmur3_u32(keys, SEED_BLOCK) | jnp.uint32(1)
        i = jnp.arange(params.k, dtype=jnp.uint32)
        block = mulhi_u32(h1, num_blocks) * jnp.uint32(BLOCK_BITS)
        off = (h2[:, None] + i[None, :] * h3[:, None]) \
            & jnp.uint32(BLOCK_BITS - 1)
        return block[:, None] + off

    def contains(bits_loc, keys):
        pos = positions(keys).astype(jnp.int32)
        word = bits_loc[jnp.clip(pos >> 5, 0, m_words - 1)]
        bit = (jnp.clip(pos, 0, m_local - 1) & 31).astype(jnp.uint32)
        probes = (word >> bit) & jnp.uint32(1)
        return jnp.all(probes == jnp.uint32(1), axis=1)

    f_pmin = "nopmin" not in name
    f_hll = "nohll" not in name
    f_counts = "nocounts" not in name

    def kernel(bits_loc, regs_loc, counts_loc, words):
        keys = (words << (32 - kw)) >> (32 - kw) if kw < 32 else words
        banks_u = words >> kw
        bank_idx = jnp.where(banks_u == sentinel, jnp.int32(-1),
                             banks_u.astype(jnp.int32))
        mask = bank_idx >= 0
        partial = contains(bits_loc, keys)
        if not full:
            return partial
        if f_pmin:
            valid = jax.lax.pmin(partial.astype(jnp.int32), "sp") == 1
        else:
            valid = partial
        outs = [valid]
        if f_hll:
            bucket, rank = hll_bucket_rank(keys, precision)
            bi = jnp.where(valid, bank_idx, -1)
            keep = (bucket >= 0) & (bucket < regs_local) & (bi >= 0) & mask
            flat = jnp.where(keep, bi * regs_local + bucket, regs_loc.size)
            regs = regs_loc.reshape(-1).at[flat].max(
                rank.astype(jnp.uint8), mode="drop").reshape(regs_loc.shape)
            outs.append(regs)
        if f_counts:
            nv = jnp.sum((valid & mask).astype(jnp.uint32))
            nr = jnp.sum(mask.astype(jnp.uint32))
            outs.append(_bump_counts(counts_loc[0], nv, nr - nv)[None])
        if "trivial2nd" in name:
            outs.append(counts_loc + jnp.uint32(1))
        if "redout" in name:
            outs.append(counts_loc
                        + jnp.sum(mask.astype(jnp.uint32)))
        if "scatonly" in name:
            lanes = jnp.arange(words.shape[0], dtype=jnp.int32)
            flat = jnp.where(mask, lanes & jnp.int32((1 << 18) - 1),
                             regs_loc.size)
            outs.append(regs_loc.reshape(-1).at[flat].max(
                jnp.uint8(1), mode="drop").reshape(regs_loc.shape))
        return tuple(outs)

    if full:
        o_specs = [P("dp")]
        dn = []
        if f_hll:
            o_specs.append(P("dp", None, "sp"))
            dn.append(1)
        if f_counts:
            o_specs.append(P("dp"))
            dn.append(2)
        if "trivial2nd" in name:
            o_specs.append(P("dp"))
        if "redout" in name:
            o_specs.append(P("dp"))
        if "scatonly" in name:
            o_specs.append(P("dp", None, "sp"))
        if "nodonate" in name:
            dn = []
        step = jax.jit(jax.shard_map(
            kernel, mesh=mesh,
            in_specs=(P("sp"), P("dp", None, "sp"), P("dp"), P("dp")),
            out_specs=tuple(o_specs),
            check_vma=False), donate_argnums=tuple(dn))
    else:
        step = jax.jit(kernel)
    bits = jax.device_put(jnp.zeros((m_words,), jnp.uint32),
                          NamedSharding(mesh, P("sp")))
    regs = jax.device_put(jnp.zeros((1, num_banks, regs_local), jnp.uint8),
                          NamedSharding(mesh, P("dp", None, "sp")))
    counts = jax.device_put(np.zeros((1, 2, 2), np.uint32),
                            NamedSharding(mesh, P("dp")))
    rng = np.random.default_rng(0)
    bs = (1 << 22 if "big22" in name else 1 << 20) if "bench" in name else 1 << 16
    keys = rng.integers(0, 1 << min(kw, 31), bs, dtype=np.uint32)
    nb_fit = (1 << (32 - kw)) - 1  # bank ids below the padding sentinel
    banks = rng.integers(0, max(1, min(num_banks, nb_fit)), bs,
                         dtype=np.uint32)
    words = jnp.asarray(pack_words(keys, banks, kw, bs))
    out = step(bits, regs, counts, words)
    jax.block_until_ready(out)
    if "bench" in name:
        # Rate of THIS executable: donated args need fresh state each
        # call chain, so rebuild the chain like the engine does.
        n_steps = 0
        bufs = [jax.device_put(np.asarray(words)) for _ in range(4)]
        cur_regs, cur_counts = None, None
        # fresh state: the first call above donated regs/counts
        regs = jax.device_put(
            np.zeros((1, num_banks, regs_local), np.uint8),
            NamedSharding(mesh, P("dp", None, "sp")))
        counts = jax.device_put(np.zeros((1, 2, 2), np.uint32),
                                NamedSharding(mesh, P("dp")))
        # warm chain
        o = step(bits, regs, counts, bufs[0])
        if full:
            cur_regs, cur_counts = o[1], o[-1]
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        while True:
            if full:
                o = step(bits, cur_regs, cur_counts, bufs[n_steps % 4])
                cur_regs, cur_counts = o[1], o[-1]
            else:
                o = step(bits, regs, counts, bufs[n_steps % 4])
            n_steps += 1
            if n_steps % 20 == 0:
                jax.block_until_ready(o)
                if time.perf_counter() - t0 > 5.0:
                    break
        jax.block_until_ready(o)
        dt = time.perf_counter() - t0
        bs_ = words.shape[0]
        print(f"fixed {name}: {n_steps * bs_ / dt / 1e6:.1f} M ev/s "
              f"({dt / n_steps * 1e3:.2f} ms/step, batch {bs_})",
              flush=True)
    print(f"fixed {name}: {probe():.3f} ms/dispatch", flush=True)


if __name__ == "__main__":
    main()
