"""CI incident smoke (ISSUE 17): a chaos run that MUST page.

One fused run with a ``persist_fail`` burst injected under the sink
breaker: every insert fails, the circuit opens, batches spill to disk
— the exact correlated breach the incident plane exists to catch.

Gates:

* the :class:`IncidentEngine` opens an incident within ONE evaluation
  tick of the breach (ticks are driven manually for determinism; the
  background thread is stopped first);
* the evidence bundle is complete — all five parts present and
  verified against the sha256 manifest in ``incident.json``;
* ``diagnosis.json`` ranks the injected cause first
  (``persist_sink_down``);
* ``doctor --incident`` replays the bundle offline and exits 0
  (open-but-diagnosed is a PASS; incomplete or undiagnosed pages).

The workdir (bundles + prom file + spill dir) ships as a CI triage
artifact on failure.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser(description="incident smoke")
    ap.add_argument("--workdir", default="/tmp/incident_smoke")
    ap.add_argument("--events", type=int, default=1 << 14)
    ap.add_argument("--frame-size", type=int, default=2048)
    args = ap.parse_args()

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    inc_dir = work / "incidents"
    prom_path = work / "incident.prom"

    from attendance_tpu import chaos, obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames

    obs.disable()
    chaos.disable()
    cfg = Config(chaos="persist_fail=1.0", chaos_seed=7,
                 persist_spill_dir=str(work / "spill"),
                 persist_breaker_failures=2,
                 persist_breaker_cooldown_s=600.0,
                 incident_dir=str(inc_dir),
                 flight_recorder=64,
                 metrics_prom=str(prom_path),
                 wire_format="word", json_chunk_decode=False)
    chaos.ensure(cfg)
    telemetry = obs.enable(cfg)
    # Drive evaluation ticks by hand: the smoke's "within one tick"
    # gate must not race the 1 Hz background thread.
    telemetry.incidents.stop()
    pipe = FusedPipeline(cfg)
    failures = []
    try:
        telemetry.incidents.tick()  # warm-up: baselines the counters
        roster, frames = generate_frames(
            args.events, args.frame_size,
            roster_size=min(cfg.bloom_filter_capacity, args.events),
            num_lectures=4, seed=17)
        pipe.preload(roster)
        producer = pipe.client.create_producer(cfg.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=args.events, idle_timeout_s=0.5)

        spilled = pipe.store.spilled_total
        print(f"[incident_smoke] chaos run done: {spilled} spilled "
              f"batch(es), breaker state "
              f"{pipe.store.breaker.state}")
        if spilled <= 0:
            failures.append("persist_fail burst spilled nothing "
                            "(chaos not wired?)")

        iid = telemetry.incidents.tick()  # breach tick
        if iid is None:
            failures.append("incident did not open within one "
                            "evaluation tick of the breach")
        else:
            inc = telemetry.incidents._open
            print(f"[incident_smoke] opened {iid}: "
                  f"conditions={sorted(inc.conditions)} "
                  f"top={inc.top_rule}")
    finally:
        pipe.cleanup()
        chaos.disable()
        obs.disable()  # finalizes the still-open incident record

    # Gate 1: bundle completeness against the sha256 manifest.
    from attendance_tpu.obs.incident import (
        EVIDENCE_PARTS, find_bundles, incident_report)
    try:
        bundles = find_bundles(inc_dir)
    except FileNotFoundError:
        bundles = []
        failures.append("no incident bundle written")
    for bundle in bundles:
        missing = [n for n in EVIDENCE_PARTS + ("diagnosis.json",)
                   if not (bundle / n).is_file()]
        if missing:
            failures.append(f"{bundle.name}: missing evidence "
                            f"part(s) {missing}")

    # Gate 2: the injected cause is ranked first.
    if bundles:
        dx = json.loads((bundles[0] / "diagnosis.json").read_text())
        top = (dx.get("ranked") or [{}])[0].get("rule")
        print(f"[incident_smoke] diagnosis top: {top}")
        if top != "persist_sink_down":
            failures.append(
                f"diagnosis ranked {top!r} first, expected "
                f"'persist_sink_down'")

    # Gate 3: the offline replay verb (exactly the CI-facing form).
    if bundles:
        text, ok = incident_report(inc_dir)
        print(text)
        if not ok:
            failures.append("doctor --incident replay FAILED")
        from attendance_tpu.cli import main as cli_main
        try:
            cli_main(["doctor", "--incident", str(inc_dir)])
            code = 0
        except SystemExit as exc:
            code = int(exc.code or 0)
        if code != 0:
            failures.append(f"doctor --incident exited {code}")

    if failures:
        print("[incident_smoke] FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[incident_smoke] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
