"""CI control soak (ISSUE 20): the controller must SAVE a run that
static flags LOSE.

Three runs over the identical frame script (overload burst + trickle)
and the identical fault script (``persist_fail=1.0`` plus a transport
partition window for the first ``HEAL_S`` seconds, then healed):

1. an **oracle** run — no faults, no controller — pins the expected
   final state (HLL counts per lecture day, deduped rows, valid
   totals);
2. a **static-baseline** run — same faults, flags frozen, no spill
   buffer, no controller. Inserts raise through the retry bound and
   dead-letter: acked events are LOST and the final state diverges
   from the oracle. The soak REQUIRES this breach — if static flags
   survive the script, the comparison proves nothing;
3. a **controlled** run — same faults, plus the persist spill buffer
   and the control plane (``control_log`` + ``control_spill_dir``).
   The breaker opens, the ladder escalates through audit widening /
   snapshot stretching to ingress admission (durable spill-and-ack),
   the heal lands, the half-open probe closes the circuit, the ladder
   de-escalates, and both spill buffers drain.

Gates on the controlled run:

* the ladder actually escalated (>= 1 escalate transition recorded)
  and settled back to ``normal`` (rung 0) — bounded flapping is
  enforced by a hard cap on total actuation records;
* circuit CLOSED at end, persist spill drained, ingress spill drained;
* zero acked-event loss: final state == oracle exactly, and nothing
  dead-lettered;
* ``doctor --actuations`` replays the actuation log and exits 0
  (schema + monotonic sequence intact);
* ``doctor --recompile-ceiling 0`` over the run's prom artifact —
  every actuation stayed inside the pre-warmed shape ladder, so the
  steady state recompiled NOTHING.

The workdir (actuation log, both spill dirs, prom file) ships as a CI
triage artifact on failure.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

NUM_EVENTS = 1 << 15
FRAME_SIZE = 512
LECTURES = 4
BURST_FRAMES = 16       # overload: sent before the pipeline starts
TRICKLE_S = 0.08        # per-frame spacing for the live tail
HEAL_S = 2.5            # fault window; identical in baseline/controlled
FAULT_SPEC = "persist_fail=1.0,partition=300ms:0.01"
MAX_ACTUATIONS = 200    # bounded-flapping ceiling


def _frames(seed: int):
    from attendance_tpu.pipeline.loadgen import generate_frames
    return generate_frames(NUM_EVENTS, FRAME_SIZE,
                           roster_size=min(50_000, NUM_EVENTS),
                           num_lectures=LECTURES, seed=1_700 + seed)


def _state(pipe) -> dict:
    counts = {int(d): pipe.count(int(d)) for d in pipe.lecture_days()}
    df = pipe.store.to_dataframe()
    return {"counts": counts, "rows": len(df),
            "valid": int(df.is_valid.sum())}


def _drive(pipe, cfg, frames, *, heal=None, max_seconds=120.0,
           idle_timeout_s=2.0):
    """Overload burst + live trickle against a running pipeline, with
    the heal callback fired at HEAL_S. Returns (terminated, errors)."""
    producer = pipe.client.create_producer(cfg.pulsar_topic)
    frames = list(frames)
    for f in frames[:BURST_FRAMES]:
        producer.send(f)

    done = threading.Event()
    errors = []

    def _run():
        try:
            pipe.run(idle_timeout_s=idle_timeout_s)
        except BaseException as exc:  # noqa: BLE001 — report, don't hang
            errors.append(exc)
        finally:
            done.set()

    worker = threading.Thread(target=_run, name="soak-pipeline",
                              daemon=True)
    worker.start()
    t0 = time.monotonic()
    healed = heal is None
    for f in frames[BURST_FRAMES:]:
        if not healed and time.monotonic() - t0 >= HEAL_S:
            heal()
            healed = True
        producer.send(f)
        time.sleep(TRICKLE_S)
        if done.is_set():
            break
    if not healed:
        # Short trickle (or early exit): the fault window still ends.
        remaining = HEAL_S - (time.monotonic() - t0)
        if remaining > 0:
            time.sleep(remaining)
        heal()
    terminated = done.wait(timeout=max_seconds)
    return terminated, errors


def _oracle(seed: int) -> dict:
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    cfg = Config(bloom_filter_capacity=50_000)
    pipe = FusedPipeline(cfg, num_banks=LECTURES)
    roster, frames = _frames(seed)
    pipe.preload(roster)
    producer = pipe.client.create_producer(cfg.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(idle_timeout_s=1.0)
    state = _state(pipe)
    pipe.cleanup()
    return state


def _baseline(seed: int, work: Path, failures) -> dict:
    """Static flags under the fault script: no spill buffer, no
    controller. The run must BREACH (dead-letters + state divergence)
    — that breach is what the controlled run is judged against."""
    from attendance_tpu import chaos, obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    cfg = Config(bloom_filter_capacity=50_000,
                 chaos=FAULT_SPEC, chaos_seed=seed,
                 quarantine_dir=str(work / "baseline-dlq"),
                 max_redeliveries=2, retry_budget_s=1.0).validate()
    inj = chaos.ensure(cfg)
    pipe = FusedPipeline(cfg, num_banks=LECTURES)
    roster, frames = _frames(seed)
    pipe.preload(roster)

    def heal():
        # ChaosSpec is frozen; the injector reads ``spec`` live on
        # every roll, so swapping it heals the sink mid-run.
        inj.spec = dataclasses.replace(inj.spec, persist_fail=0.0,
                                       partition=0.0)

    terminated, errors = _drive(pipe, cfg, frames, heal=heal)
    if not terminated or errors:
        failures.append(f"baseline run wedged/raised: {errors!r}")
        return {}
    state = _state(pipe)
    dead = pipe.metrics.dead_lettered
    pipe.cleanup()
    chaos.disable()
    obs.disable()
    print(f"[control_soak] baseline: dead_lettered={dead} "
          f"state={state}")
    return {"state": state, "dead_lettered": dead}


def _controlled(seed: int, work: Path, failures) -> dict:
    from attendance_tpu import chaos, obs
    from attendance_tpu.config import Config
    from attendance_tpu.control import read_actuations
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    act_log = work / "actuations.jsonl"
    prom = work / "metrics.prom"
    ingress = work / "ingress-spill"
    cfg = Config(bloom_filter_capacity=50_000,
                 chaos=FAULT_SPEC, chaos_seed=seed,
                 quarantine_dir=str(work / "controlled-dlq"),
                 max_redeliveries=2, retry_budget_s=1.0,
                 persist_spill_dir=str(work / "persist-spill"),
                 persist_breaker_failures=2,
                 persist_breaker_cooldown_s=0.25,
                 snapshot_dir=str(work / "snaps"),
                 snapshot_mode="delta", snapshot_every_batches=8,
                 control_log=str(act_log),
                 control_spill_dir=str(ingress),
                 # Each half-open probe cycle under a still-sick sink
                 # costs TWO ladder transitions (shed -> probe ->
                 # shed); with a 0.25 s breaker cooldown the default
                 # flap limit of 8/min would freeze the ladder at shed
                 # before the heal lands. Budget ~8 probe cycles.
                 control_dwell_s=0.3, control_clear_ticks=2,
                 control_flap_limit=24,
                 metrics_prom=str(prom),
                 metrics_interval_s=0.1).validate()
    telemetry = obs.enable(cfg)
    inj = chaos.ensure(cfg)
    pipe = FusedPipeline(cfg, num_banks=LECTURES)
    roster, frames = _frames(seed)
    pipe.preload(roster)

    def heal():
        # ChaosSpec is frozen; the injector reads ``spec`` live on
        # every roll, so swapping it heals the sink mid-run.
        inj.spec = dataclasses.replace(inj.spec, persist_fail=0.0,
                                       partition=0.0)

    terminated, errors = _drive(pipe, cfg, frames, heal=heal)
    report: dict = {}
    try:
        if not terminated or errors:
            failures.append(f"controlled run wedged/raised: {errors!r}")
            return report

        # Let the controller settle: pressure is gone, so the ladder
        # must walk back to rung 0 (dwell-paced) on its own.
        eng = telemetry.control
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and eng.ladder.rung != 0:
            time.sleep(0.2)
        rung = eng.ladder.rung
        if rung != 0:
            failures.append(
                f"controller never de-escalated (rung {rung} "
                f"after settle window)")

        store = pipe.store
        if store.breaker.opened_total == 0:
            failures.append("persist_fail never opened the circuit "
                            "(fault script not wired?)")
        if store.breaker.state != "closed":
            failures.append(f"circuit ended {store.breaker.state!r}, "
                            f"not closed")
        if store.spill_pending != 0:
            failures.append(f"{store.spill_pending} persist spill "
                            f"batch(es) stranded")
        stranded = sorted(ingress.glob("ingress-*.bin")) \
            if ingress.is_dir() else []
        if stranded:
            failures.append(f"{len(stranded)} ingress spill file(s) "
                            f"stranded: {[p.name for p in stranded]}")
        if pipe.metrics.dead_lettered:
            failures.append(f"controlled run dead-lettered "
                            f"{pipe.metrics.dead_lettered} frame(s) "
                            f"(acked loss)")

        report["state"] = _state(pipe)
        report["spilled"] = store.spilled_total
        report["drained"] = store.drained_total
        report["circuit_opened"] = store.breaker.opened_total
        report["ingress_spilled"] = eng.admission.spilled_total
        report["shed"] = eng.admission.shed_total
    finally:
        pipe.cleanup()
        chaos.disable()
        obs.disable()  # final prom write + actuation log close

    records, problems = read_actuations(str(act_log))
    report["actuations"] = len(records)
    for p in problems:
        failures.append(f"actuation log: {p}")
    escalations = [r for r in records
                   if r["knob"] == "ladder.rung"
                   and r["direction"] == "escalate"]
    if not escalations:
        failures.append("controller never escalated the ladder under "
                        "the fault script")
    if records and len(records) > MAX_ACTUATIONS:
        failures.append(f"{len(records)} actuations recorded — "
                        f"flapping (cap {MAX_ACTUATIONS})")
    peak = max((r["rung"] for r in records), default=0)
    report["peak_rung"] = peak
    print(f"[control_soak] controlled: {report}")
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description="control-plane chaos soak")
    ap.add_argument("--workdir", default="/tmp/control_soak")
    ap.add_argument("--seed", type=int, default=20)
    args = ap.parse_args()

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)

    from attendance_tpu import chaos, obs

    chaos.disable()
    obs.disable()
    failures: list = []

    want = _oracle(args.seed)
    print(f"[control_soak] oracle: {want}")

    base = _baseline(args.seed, work, failures)
    if base:
        # The baseline MUST breach — acked loss under static flags is
        # the condition the controller exists to prevent.
        if base["dead_lettered"] == 0:
            failures.append("baseline dead-lettered nothing — fault "
                            "script too soft to prove anything")
        if base["state"] == want:
            failures.append("baseline state equals oracle — static "
                            "flags survived; comparison is vacuous")

    ctl = _controlled(args.seed, work, failures)
    if ctl.get("state") is not None and ctl["state"] != want:
        failures.append(f"controlled state diverged from oracle: "
                        f"{ctl['state']} != {want}")

    # Offline replay verbs, exactly as CI would run them.
    from attendance_tpu.cli import main as cli_main

    def _cli(argv):
        try:
            cli_main(argv)
            return 0
        except SystemExit as exc:
            return int(exc.code or 0)

    act_log = work / "actuations.jsonl"
    if act_log.is_file():
        code = _cli(["doctor", "--actuations", str(act_log)])
        if code != 0:
            failures.append(f"doctor --actuations exited {code}")
    else:
        failures.append("no actuation log written")

    prom = work / "metrics.prom"
    if prom.is_file():
        code = _cli(["doctor", str(prom), "--recompile-ceiling", "0"])
        if code != 0:
            failures.append(
                f"doctor --recompile-ceiling 0 exited {code} — a "
                f"shape-changing actuation escaped the ladder")
    else:
        failures.append("no prom artifact written")

    if failures:
        print("[control_soak] FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[control_soak] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
