"""CI profile smoke (ISSUE 15): a short fused run with the host
sampling profiler live, gated on the attribution plane's contracts.

Two feeds through ONE pipeline:

1. **Warmup run** — compiles the jitted steps (expected, counted as
   warmup); the end of the first completed run loop marks the
   recompile tracker warm.
2. **Steady run** — identically shaped frames; any NEW shape
   fingerprint here is a steady-state recompile, which the doctor
   gate refuses at ``--recompile-ceiling 0``.

Gates:

* steady-state recompiles after warmup == 0 (``doctor`` over the
  run's own prom artifact with ``--recompile-ceiling 0`` — an absent
  counter fails loudly, never vacuously);
* the attribution table parses (``telemetry --attribution`` over the
  written ``attribution.json`` renders a non-empty stage table with
  samples > 0);
* the flamegraph artifacts exist and are well-formed (non-empty
  collapsed stacks; the Perfetto stage timeline loads as JSON).

The workdir (profile artifacts + prom file) ships as a CI triage
artifact on failure.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser(description="profile smoke")
    ap.add_argument("--workdir", default="/tmp/profile_smoke")
    ap.add_argument("--profile-hz", type=float, default=29.0)
    ap.add_argument("--events", type=int, default=1 << 16)
    ap.add_argument("--frame-size", type=int, default=4096)
    args = ap.parse_args()

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    prof_dir = work / "profile"
    prom_path = work / "profile.prom"

    from attendance_tpu import obs
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames

    obs.disable()
    cfg = Config(profile_hz=args.profile_hz,
                 profile_out=str(prof_dir),
                 metrics_prom=str(prom_path),
                 flight_recorder=64,
                 # Deterministic shapes: auto's backpressure ladder
                 # may legitimately narrow mid-steady-run, and the
                 # chunk consumer coalesces backlog frames into
                 # timing-dependent padded shapes — both are REAL
                 # compiles, not leaks, and the smoke gates the leak
                 # class only. Fixed wire + per-message frames keep
                 # every dispatch the same program.
                 wire_format="word", json_chunk_decode=False)
    telemetry = obs.enable(cfg)
    pipe = FusedPipeline(cfg)
    failures = []
    try:
        roster, frames = generate_frames(
            args.events, args.frame_size,
            roster_size=min(cfg.bloom_filter_capacity, args.events),
            num_lectures=4, seed=11)
        pipe.preload(roster)
        producer = pipe.client.create_producer(cfg.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=args.events, idle_timeout_s=0.5)
        warm_compiles = telemetry.recompiles.total
        print(f"[profile_smoke] warmup: {warm_compiles} compile(s), "
              f"{telemetry.profiler.samples} samples")
        if not telemetry.recompiles.warm:
            failures.append("tracker not warm after the first run")
        # Steady feed: identical shapes — SAME seed, because a fresh
        # seed's roster can change the max-key bit width, which is a
        # legitimately new program variant, not the leak class this
        # smoke gates (idempotent sketches make the replay harmless).
        _, frames2 = generate_frames(
            args.events, args.frame_size,
            roster_size=min(cfg.bloom_filter_capacity, args.events),
            num_lectures=4, seed=11)
        for f in frames2:
            producer.send(f)
        pipe.run(max_events=2 * args.events, idle_timeout_s=0.5)
        steady = telemetry.recompiles.steady
        print(f"[profile_smoke] steady run: {steady} steady-state "
              f"recompile(s), {telemetry.profiler.samples} samples")
        samples = telemetry.profiler.samples
        if samples <= 0:
            failures.append("profiler folded zero samples")
    finally:
        pipe.cleanup()
        obs.disable()  # stops the sampler, writes artifacts + prom

    # Gate 1: doctor over the run's own prom artifact with the
    # recompile ceiling (exactly the CI-facing verb form).
    from attendance_tpu.obs.slo import doctor_report

    text, ok = doctor_report([str(prom_path)], recompile_ceiling=0)
    print(text)
    if not ok:
        failures.append("doctor --recompile-ceiling 0 FAILED")

    # Gate 2: the attribution table parses and names stages.
    from attendance_tpu.obs.profiler import (
        ATTRIBUTION_FILE, FOLDED_FILE, TRACE_FILE,
        format_attribution_table)

    att_path = prof_dir / ATTRIBUTION_FILE
    try:
        doc = json.loads(att_path.read_text())
        table = format_attribution_table(doc)
        print(table)
        if doc.get("kind") != "attribution" \
                or doc.get("samples_total", 0) <= 0 \
                or "stage" not in table:
            failures.append("attribution table empty or malformed")
    except Exception as exc:  # noqa: BLE001 — the gate itself
        failures.append(f"attribution table unparseable: {exc!r}")

    # Gate 3: flamegraph artifacts well-formed.
    try:
        folded = (prof_dir / FOLDED_FILE).read_text()
        if not folded.strip():
            failures.append("profile.folded is empty")
        for line in folded.strip().splitlines():
            int(line.rsplit(" ", 1)[1])
        trace = json.loads((prof_dir / TRACE_FILE).read_text())
        if not any(e.get("ph") == "X"
                   for e in trace.get("traceEvents", [])):
            failures.append("profile_trace.json has no stage slices")
    except Exception as exc:  # noqa: BLE001 — the gate itself
        failures.append(f"flamegraph artifacts unreadable: {exc!r}")

    if failures:
        print("[profile_smoke] FAIL:", "; ".join(failures))
        return 1
    print("[profile_smoke] PASS (steady recompiles == 0, attribution "
          "parses, flamegraph artifacts well-formed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
