"""Federation failover soak (CI gate): 3 shard workers over a REAL
socket broker, one SIGKILLed mid-run, a takeover worker recovering its
shard — the merged global view must equal the no-crash single-process
oracle exactly.

Choreography:

1. start a socket BrokerServer subprocess; run the federation
   aggregator IN THIS PROCESS (driver asserts against its live merged
   view) with telemetry -> a prom artifact for the doctor gate;
2. spawn 3 ``attendance_tpu.federation.worker`` subprocesses
   (``--data-plane socket``: each consumes its shard topic from the
   broker, checkpoints in delta mode, gossips every fence);
3. publish each shard's deterministic workload, release the go-gate;
4. SIGKILL worker w1 the moment its snapshot chain holds a delta
   (mid-run by construction: unacked frames requeue on disconnect);
5. gate A — the aggregator declares w1 dead within the budget, bumps
   the shard-map version, orphans the shard, and folds w1's durable
   base+delta chain;
6. spawn the takeover worker (same id, same chain dir, ``--takeover``)
   — it restores the chain, replays the quarantine, drains the
   requeued remainder, and re-claims the shard at a higher
   incarnation (gate B);
7. gate C — merged view == no-crash oracle (a no-crash FEDERATED run
   over the same shards, merged with the CRDT twins): byte-identical
   Bloom words, per-day register equality, zero Bloom false negatives
   over the full roster (the driver's regenerated roster IS the exact
   shadow), and counters never BELOW the truth — sketches and the
   store are exactly-once under replay, cumulative counters are
   at-least-once across a SIGKILL (a kill between a barrier's
   durability point and its group-commit ack makes the takeover
   reprocess that interval), so the events/valid/invalid excess must
   stay within the group-commit window and reconcile;
8. gate D — ``doctor`` over the aggregator's prom artifact with
   ``--merge-lag-ceiling``.

Exit 0 = all gates pass. Run on CPU:
``JAX_PLATFORMS=cpu python tools/federation_soak.py``.
"""

from __future__ import annotations

import argparse
import json
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

K = 3
GOSSIP_TOPIC = "fed-soak-gossip"
BASE_TOPIC = "attendance-events"
KILLED = 1  # shard/worker index that dies


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", flush=True)
    return 1


def _worker_log(workdir: Path, shard: int) -> Path:
    return workdir / f"worker-{shard}.log"


def _spawn_worker(addr: str, workdir: Path, shard: int, n_events: int,
                  seed: int, *, takeover: bool = False,
                  ready: str = "", go: str = "",
                  fleet_push: str = "",
                  chaos_spec: str = "") -> subprocess.Popen:
    cmd = [sys.executable, "-m", "attendance_tpu.federation.worker",
           "--worker", f"w{shard}", "--shard", str(shard),
           "--num-shards", str(K), "--broker", addr,
           "--gossip-topic", GOSSIP_TOPIC,
           "--workdir", str(workdir), "--data-plane", "socket",
           "--num-events", str(n_events), "--seed", str(seed),
           "--snapshot-every", "2", "--idle-timeout-s", "4"]
    if chaos_spec:
        cmd += ["--chaos", chaos_spec, "--chaos-seed", str(seed)]
    if fleet_push:
        cmd += ["--fleet-push", fleet_push]
    if takeover:
        cmd.append("--takeover")
    if ready:
        cmd += ["--ready-file", ready]
    if go:
        cmd += ["--go-file", go]
    # Output goes to a per-worker FILE (takeover appends after its
    # predecessor), never an undrained pipe: a saturated runner's
    # retry-warning tracebacks can fill a 64 KB pipe and deadlock the
    # worker mid-run. The files double as triage artifacts.
    with open(_worker_log(workdir, shard), "a") as fh:
        return subprocess.Popen(cmd, stdout=fh,
                                stderr=subprocess.STDOUT, text=True,
                                cwd=str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/federation_soak")
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--frames-per-shard", type=int, default=24)
    ap.add_argument("--dead-after-s", type=float, default=3.0,
                    help="peer silence budget; generous by default — "
                    "a saturated 2-core host can stall heartbeat "
                    "delivery past 2s, and a spuriously-dead LIVE "
                    "peer, while convergence-safe (its chain folds "
                    "idempotently and fresh gossip revives it), makes "
                    "the takeover gates noisy")
    ap.add_argument("--merge-lag-ceiling", type=float, default=5.0,
                    help="doctor merge-lag p99 gate (generous: "
                    "shared CI runners)")
    ap.add_argument("--partition-spec",
                    default="partition=1200ms:0.04",
                    help="chaos spec injected into worker w2 "
                    "(one-way gossip + consume blackhole windows; "
                    "'' disables)")
    ap.add_argument("--no-disk-corrupt", action="store_true",
                    help="skip the deterministic post-kill delta "
                    "corruption + peer-assisted repair gates")
    args = ap.parse_args()

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    prom = work / "metrics.prom"

    from attendance_tpu import obs
    from attendance_tpu.config import Config
    from attendance_tpu.federation.gossip import Aggregator
    from attendance_tpu.federation.shard import shard_topic
    from attendance_tpu.federation.worker import (
        DEFAULT_BATCH, build_workload)
    from attendance_tpu.serve.engine import QueryEngine
    from attendance_tpu.transport.socket_broker import (
        SocketClient, spawn_broker)

    n_events = args.frames_per_shard * DEFAULT_BATCH

    # Fleet collector (ISSUE 9): the driver hosts it, every role —
    # broker subprocess, 3+1 workers, the in-process aggregator —
    # pushes registry snapshots + span batches to it, and gate E runs
    # `doctor --fleet` over the persisted artifact dir (ONE verdict
    # table, per-role rows + fleet-wide merge-lag gate).
    from attendance_tpu.obs.fleet import FleetCollector

    fleet_dir = work / "fleet"
    collector = FleetCollector(directory=str(fleet_dir), port=0).start()
    print(f"[soak] fleet collector on {collector.address} "
          f"(artifacts -> {fleet_dir})", flush=True)

    telemetry = obs.enable(Config(metrics_prom=str(prom),
                                  metrics_interval_s=0.2,
                                  fleet_push=collector.address,
                                  fleet_role="aggregator",
                                  fleet_instance="agg",
                                  fleet_push_interval_s=0.5))

    broker_proc, addr = spawn_broker(cwd=REPO,
                                     fleet_push=collector.address)
    agg_client = SocketClient(addr)
    agg = Aggregator(client=agg_client, topic=GOSSIP_TOPIC,
                     num_shards=K, dead_after_s=args.dead_after_s,
                     obs=telemetry).start()
    workers: list = []
    try:
        go = work / "go"
        for s in range(K):
            ready = work / f"ready-{s}"
            workers.append(_spawn_worker(
                addr, work, s, n_events, args.seed,
                ready=str(ready), go=str(go),
                fleet_push=collector.address,
                # w2 runs under injected PARTITION windows (one-way
                # gossip + consume blackholes): the broker retains
                # through consume silence, and the assured final
                # fed_flush re-asserts through gossip loss — gate C's
                # oracle equality is the convergence proof.
                chaos_spec=(args.partition_spec if s == 2 else "")))
        deadline = time.time() + 300
        for s in range(K):
            while not (work / f"ready-{s}").exists():
                if workers[s].poll() is not None:
                    return _fail(f"worker w{s} died before ready:\n"
                                 + _worker_log(work, s).read_text())
                if time.time() > deadline:
                    return _fail(f"worker w{s} never became ready")
                time.sleep(0.02)

        # Publish every shard's deterministic workload, then open the
        # gate. The driver's regenerated frames double as the oracle's
        # input below.
        client = SocketClient(addr)
        all_frames: dict = {}
        roster = None
        for s in range(K):
            roster, _, frames = build_workload(
                args.seed, s, K, n_events)
            all_frames[s] = frames
            producer = client.create_producer(
                shard_topic(BASE_TOPIC, s))
            for f in frames:
                producer.send(f)
            producer.close()
        go.touch()
        print(f"[soak] {K} workers live, {n_events} events/shard "
              f"published", flush=True)

        # Kill w1 the moment its chain holds a delta (durable state
        # exists, backlog still in flight).
        chain = work / f"chain-{KILLED}" / "CHAIN.json"
        deadline = time.time() + 120
        while True:
            if chain.exists() and json.loads(
                    chain.read_text()).get("deltas"):
                break
            if workers[KILLED].poll() is not None:
                return _fail("w1 exited before the kill "
                             "(raise --frames-per-shard)")
            if time.time() > deadline:
                return _fail("w1 never wrote a delta")
            time.sleep(0.01)
        workers[KILLED].send_signal(signal.SIGKILL)
        workers[KILLED].wait()
        print("[soak] SIGKILLed w1 mid-run; chain: "
              + chain.read_text(), flush=True)

        # Gate A: dead declaration + shard orphaned + chain folded.
        deadline = time.time() + args.dead_after_s + 30
        while True:
            stats = agg.stats()
            w1 = stats["workers"].get(f"w{KILLED}")
            if (w1 is not None and not w1["up"]
                    and f"w{KILLED}" in stats["recovered_chains"]):
                break
            if time.time() > deadline:
                return _fail("aggregator never declared w1 dead / "
                             f"recovered its chain: {stats}")
            time.sleep(0.05)
        map_v_dead = stats["shard_map"]["version"]
        if stats["shard_map"]["owners"][KILLED] is not None:
            return _fail(f"w1's shard not orphaned: {stats['shard_map']}")
        if map_v_dead < 2:
            return _fail("shard-map version did not bump on failover")
        dead_incarnation = stats["workers"][f"w{KILLED}"]["incarnation"]
        print(f"[soak] gate A: w1 dead, shard orphaned at map v"
              f"{map_v_dead}, chain recovered "
              f"({stats['recovered_chains']})", flush=True)

        # Storage rot on the dead peer's chain (the integrity plane's
        # acceptance choreography): flip one byte mid-file in a
        # manifest-named delta AFTER the aggregator recovered the
        # chain (its retained per-worker view already holds the
        # delta's banks — they were gossiped at their fences). The
        # takeover's restore must classify the rot, quarantine the
        # file, and repair PEER-ASSISTED via a re-assert request.
        corrupted_delta = ""
        if not args.no_disk_corrupt:
            chain_doc = json.loads(chain.read_text())
            corrupted_delta = chain_doc["deltas"][-1]
            victim = work / f"chain-{KILLED}" / corrupted_delta
            raw = bytearray(victim.read_bytes())
            raw[len(raw) // 2] ^= 0xFF
            victim.write_bytes(bytes(raw))
            print(f"[soak] injected disk_corrupt into {victim.name} "
                  "(post-fsync bit flip)", flush=True)

        # Takeover worker: same id, same chain dir, higher incarnation.
        takeover = _spawn_worker(addr, work, KILLED, n_events,
                                 args.seed, takeover=True,
                                 fleet_push=collector.address)
        workers.append(takeover)

        # Wait for every worker to finish (w0/w2 drain + exit; the
        # takeover drains the requeued remainder).
        deadline = time.time() + 300
        for w in (workers[0], workers[2], takeover):
            while w.poll() is None:
                if time.time() > deadline:
                    return _fail("a worker never finished")
                time.sleep(0.1)
        reports = {}
        for w, shard in ((workers[0], 0), (workers[2], 2),
                         (takeover, KILLED)):
            out = _worker_log(work, shard).read_text().strip()
            if w.returncode != 0:
                return _fail(f"worker rc={w.returncode}:\n{out}")
            # The takeover appends to the killed worker's log; the
            # LAST report line is always the surviving run's.
            rep = json.loads([ln for ln in out.splitlines()
                              if ln.startswith("{")][-1])
            reports[(rep["worker"], rep["takeover"])] = rep
        print(f"[soak] workers done: { {k: v['events'] for k, v in reports.items()} }",
              flush=True)

        # Gate B: the takeover re-claimed the shard at a higher
        # incarnation (its gossip marked the peer back up).
        deadline = time.time() + 30
        while True:
            stats = agg.stats()
            w1 = stats["workers"].get(f"w{KILLED}")
            if (w1 is not None and w1["up"]
                    and w1["incarnation"] > dead_incarnation
                    and stats["shard_map"]["owners"][KILLED]
                    == f"w{KILLED}"):
                break
            if time.time() > deadline:
                return _fail(f"takeover never re-claimed the shard: "
                             f"{stats}")
            time.sleep(0.05)
        print(f"[soak] gate B: takeover re-claimed shard {KILLED} "
              f"(incarnation {w1['incarnation']:.3f} > "
              f"{dead_incarnation:.3f})", flush=True)

        # Gate B2: the rot was detected, quarantined, and repaired —
        # never crash-looped. The corrupt delta must sit in the chain
        # dir's integrity-quarantine with its sidecar, the manifest
        # must have stopped naming it, and the takeover's log must
        # show the peer-assisted repair.
        if corrupted_delta:
            qdir = work / f"chain-{KILLED}" / "integrity-quarantine"
            if not (qdir / corrupted_delta).exists():
                return _fail(f"corrupt delta {corrupted_delta} was "
                             "never quarantined")
            man_now = json.loads(chain.read_text())
            if corrupted_delta in man_now.get("deltas", []):
                return _fail("manifest still names the quarantined "
                             f"delta: {man_now}")
            log = _worker_log(work, KILLED).read_text()
            if "folded peer re-assert" not in log:
                return _fail("takeover log shows no peer-assisted "
                             "repair (re-assert never arrived):\n"
                             + log[-2000:])
            print(f"[soak] gate B2: {corrupted_delta} quarantined, "
                  "chain truncated, peer re-assert folded",
                  flush=True)

        # Drain the gossip tail synchronously, then assert.
        agg.pause()
        while agg.poll(timeout_ms=200) > 0:
            pass

        # Gate C: merged view == no-crash oracle. The oracle is a
        # NO-CRASH FEDERATED run — K in-process pipelines over the
        # same shard slices and frames, merged host-side with the CRDT
        # twins. (A single full-population pipeline is NOT register-
        # equivalent: its denser Bloom filter admits a different set
        # of false-positive invalid keys into the day HLLs, so only
        # the same topology run without the SIGKILL is the honest
        # "what did the crash cost" baseline.)
        import numpy as np

        from attendance_tpu.federation.shard import shard_of_keys
        from attendance_tpu.models.bloom import bloom_or_words_np
        from attendance_tpu.models.fused import decode_counts
        from attendance_tpu.models.hll import hll_merge_np
        from attendance_tpu.pipeline.fast_path import FusedPipeline
        from attendance_tpu.transport.memory_broker import (
            MemoryBroker, MemoryClient)

        oracle_by_day: dict = {}
        owords = None
        ovalid = oinvalid = 0
        for s in range(K):
            oclient = MemoryClient(MemoryBroker())
            opipe = FusedPipeline(Config(transport_backend="memory"),
                                  client=oclient, num_banks=16)
            opipe.preload(roster[shard_of_keys(roster, K) == s])
            oproducer = oclient.create_producer("attendance-events")
            for f in all_frames[s]:
                oproducer.send(f)
            opipe.run(max_events=n_events, idle_timeout_s=3.0)
            if opipe.metrics.events != n_events:
                return _fail(f"oracle shard {s} processed "
                             f"{opipe.metrics.events} != {n_events}")
            words = np.asarray(opipe.state.bloom_bits)
            owords = (words if owords is None
                      else bloom_or_words_np(owords, words))
            oregs = np.asarray(opipe.state.hll_regs)
            for day, b in opipe._bank_of.items():
                cur = oracle_by_day.get(int(day))
                oracle_by_day[int(day)] = (
                    oregs[b].copy() if cur is None
                    else hll_merge_np(cur, oregs[b])[0])
            v, i = decode_counts(np.asarray(opipe.state.counts))
            ovalid += v
            oinvalid += i
            opipe.cleanup()

        # Counter contract across a SIGKILL (same as the delta-crash
        # smoke's): sketch state and the store are exactly-once
        # (idempotent merges / last-write-wins dedup), but cumulative
        # COUNTERS are at-least-once — a kill landing between a
        # barrier's durability point and its group-commit ack makes
        # the takeover reprocess (and recount) that interval's frames.
        # Gate: never BELOW the true total (acked loss), above it by
        # at most two group-commit intervals.
        total = K * n_events
        overcount = agg.view.events - total
        ceiling = 2 * 2 * 8_192  # 2 barriers x (snapshot-every=2 x batch)
        if overcount < 0:
            return _fail(f"merged events {agg.view.events} < {total} "
                         "— acked events were LOST across the "
                         "failover")
        if overcount > ceiling:
            return _fail(f"merged events overcount {overcount} "
                         f"exceeds the group-commit window ({ceiling})"
                         " — takeover is replaying acked frames")
        print(f"[soak] events {agg.view.events} (true {total}, "
              f"bounded at-least-once overcount {overcount})",
              flush=True)
        if not (agg.view.bloom_words == owords).all():
            return _fail("merged Bloom words differ from the no-crash "
                         "oracle filter union")
        got_by_day = agg.view.regs_by_day()
        if set(got_by_day) != set(oracle_by_day):
            return _fail(f"day sets differ: {sorted(got_by_day)} vs "
                         f"{sorted(oracle_by_day)}")
        for day, row in oracle_by_day.items():
            if not (got_by_day[day] == row).all():
                return _fail(f"registers for day {day} differ from "
                             "the oracle")
        gvalid, ginvalid = decode_counts(agg.view.counts_array())
        if gvalid < ovalid or ginvalid < oinvalid:
            return _fail(f"valid/invalid counters regressed: "
                         f"({gvalid}, {ginvalid}) vs oracle "
                         f"({ovalid}, {oinvalid})")
        if (gvalid - ovalid) + (ginvalid - oinvalid) != overcount:
            return _fail(
                f"valid/invalid excess (+{gvalid - ovalid}, "
                f"+{ginvalid - oinvalid}) does not reconcile with the "
                f"events overcount {overcount}")
        # Exact-shadow membership audit: zero false negatives over the
        # full (driver-regenerated) roster.
        engine = QueryEngine(agg.mirror)
        misses = int((~engine.bf_exists(roster)).sum())
        if misses:
            return _fail(f"{misses} Bloom false negatives over the "
                         "federated view")
        print(f"[soak] gate C: merged state == oracle ({total} events,"
              f" {len(got_by_day)} days, zero false negatives)",
              flush=True)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        try:
            agg.stop()
            agg_client.close()
        except Exception:
            pass
        broker_proc.kill()
        broker_proc.wait()
        obs.disable()  # writes the final exposition block + last push
        collector.stop()  # flushes FLEET.json + the stitched trace

    # Gate D: doctor over the aggregator's prom artifact.
    doctor = subprocess.run(
        [sys.executable, "-m", "attendance_tpu.cli", "doctor",
         str(prom), "--merge-lag-ceiling",
         str(args.merge_lag_ceiling)], cwd=str(REPO))
    if doctor.returncode != 0:
        return _fail(f"doctor exited {doctor.returncode}")

    # Gate E: doctor --fleet over the collected artifact dir — ONE
    # verdict table with per-role rows and the fleet-wide merge-lag
    # gate judged over the MERGED data (exit 1 on breach).
    doctor = subprocess.run(
        [sys.executable, "-m", "attendance_tpu.cli", "doctor",
         "--fleet", str(fleet_dir), "--merge-lag-ceiling",
         str(args.merge_lag_ceiling)], cwd=str(REPO))
    if doctor.returncode != 0:
        return _fail(f"doctor --fleet exited {doctor.returncode}")

    # Gate F: the stitched Perfetto export crosses the process
    # boundary — at least one aggregator fed_merge span must parent
    # under a WORKER's fence_publish span (the traceparent rode the
    # gossip frame header).
    trace = json.loads((fleet_dir / "fleet_trace.json").read_text())
    slices = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"]
    fences = {e["args"]["span_id"]: e for e in slices
              if e["name"] == "fence_publish"}
    merges = [e for e in slices if e["name"] == "fed_merge"]
    stitched = [e for e in merges
                if e["args"].get("parent_span_id") in fences]
    if not stitched:
        return _fail(
            f"no fed_merge span parents under a fence_publish span "
            f"({len(merges)} merges, {len(fences)} fences collected) "
            "— federated trace stitching broke")
    print(f"[soak] gate F: {len(stitched)}/{len(merges)} fed_merge "
          "spans stitched under worker fence_publish spans",
          flush=True)

    # Gate G: the surviving workdir scrubs CLEAN — after repair, no
    # chain/spill/quarantine artifact anywhere in the soak's output
    # fails its digest (the quarantined rot itself sits in the
    # excluded integrity-quarantine/ dir, preserved for triage).
    scrub = subprocess.run(
        [sys.executable, "-m", "attendance_tpu.cli", "scrub",
         str(work)], cwd=str(REPO))
    if scrub.returncode != 0:
        return _fail(f"scrub over the surviving workdir exited "
                     f"{scrub.returncode}")
    print("[soak] gate G: surviving workdir scrubs clean", flush=True)
    print("PASS: federation soak (dead-peer takeover, disk-rot "
          "repair, partitioned peer, oracle-equal merged state, zero "
          "false negatives, doctor + fleet + scrub gates)",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
